"""Shared CLI flag surface.

The reference splits flags across three argparse parsers with cross-process
coupling (``src/server.py:270-274``, ``src/client.py:56-59``,
``src/main.py:20-26`` — the trainer's parser runs inside the client process
because of import-time side effects). fedtpu keeps the reference's flag
*names* where they exist (``-c/--compressFlag``, ``-a/--address``,
``-r/--resume``, ``--lr``, ``--p``) and adds explicit flags for everything
the reference hardcodes (model, dataset, rounds, client registry).
"""

from __future__ import annotations

import argparse

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RetryPolicy,
    RoundConfig,
    ScreenConfig,
    SimConfig,
)
from fedtpu.data import dataset_info


def add_platform_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu", "cuda"],
        help="pin the jax platform. Setting JAX_PLATFORMS in the environment "
        "is NOT always equivalent: a registered TPU plugin can ignore it "
        "(and a wedged remote TPU backend then hangs the process); this flag "
        "uses jax.config.update, which wins.",
    )
    p.add_argument(
        "--fake-devices",
        default=None,
        type=int,
        metavar="N",
        help="with --platform cpu: present N virtual CPU devices "
        "(the standard mesh-testing trick, SURVEY.md §4)",
    )


def apply_platform_flag(args) -> None:
    """Apply --platform/--fake-devices. Must run before any jax device query;
    safe because fedtpu modules import jax lazily enough that the backend is
    uninitialised until the first model/data build."""
    if getattr(args, "fake_devices", None):
        from fedtpu.utils.platform import force_host_device_count

        force_host_device_count(args.fake_devices)
    if getattr(args, "platform", None):
        import jax

        jax.config.update("jax_platforms", args.platform)


def add_model_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--model",
        default="MobileNet",
        help="architecture (reference hardcodes MobileNet, src/main.py:69)",
    )
    p.add_argument(
        "--dataset",
        default="cifar10",
        choices=["cifar10", "cifar100", "mnist", "synthetic"],
    )
    p.add_argument("--lr", default=0.1, type=float, help="learning rate")
    p.add_argument(
        "--schedule",
        default="constant",
        choices=["constant", "cosine"],
        help="LR schedule. 'constant' matches the reference's effective "
        "behavior (its cosine scheduler is constructed but never stepped, "
        "src/main.py:231-242); 'cosine' is the schedule it intended",
    )
    p.add_argument("--batch-size", default=128, type=int)
    p.add_argument(
        "--momentum-dtype", default="float32",
        choices=["float32", "bfloat16"],
        help="HBM dtype of the per-client momentum buffers. bfloat16 is a "
        "flagged NON-PARITY mode that halves optimizer-state bandwidth "
        "(update math stays f32; see OptimizerConfig.momentum_dtype)",
    )
    p.add_argument(
        "--eval-batch-size", default=100, type=int,
        help="test-set batch size (reference: src/main.py:56). Must not "
        "exceed the eval set size — lower it for small/truncated datasets",
    )
    p.add_argument("--seed", default=0, type=int)
    p.add_argument(
        "--num-examples",
        default=None,
        type=int,
        help="truncate the dataset (for smoke runs)",
    )
    p.add_argument(
        "-c",
        "--compressFlag",
        default="N",
        help="Y enables update compression (reference: transport gzip; here "
        "additionally top-k delta compression on the TPU path)",
    )


def parse_compression(spec: str):
    """Parse a ``--compression`` spec into ``(codec, rotq_bits | None)``.

    Accepts a bare codec name or the parameterized ``rotq:bits=B`` form
    (argparse ``type=`` hook, so a bad spec fails at parse time with a
    usage error instead of deep inside config validation)."""
    codec, _, rest = spec.partition(":")
    bits = None
    if rest:
        if codec != "rotq" or not rest.startswith("bits="):
            raise argparse.ArgumentTypeError(
                f"bad compression spec {spec!r}: only rotq takes a "
                "parameter, as rotq:bits=B"
            )
        try:
            bits = int(rest[len("bits="):])
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"bad compression spec {spec!r}: bits must be an integer"
            )
        if bits not in (1, 2, 4, 8):
            raise argparse.ArgumentTypeError(
                f"rotq bits must be 1, 2, 4 or 8, got {bits}"
            )
    if codec not in ("none", "topk", "int8", "rotq", "randk"):
        raise argparse.ArgumentTypeError(
            f"unknown codec {codec!r}; have none | topk | int8 | "
            "rotq[:bits=B] | randk"
        )
    return codec, bits


def add_compression_flags(p: argparse.ArgumentParser) -> None:
    """Delta-codec flags, shared by the simulated engine CLI, the gRPC
    server AND the gRPC client (the client encodes its own wire payloads,
    so it needs the codec + layout choice too)."""
    p.add_argument(
        "--compression",
        default=None,
        type=parse_compression,
        help="delta codec: none | topk | int8 | rotq[:bits=B] | randk "
        "(rotq/randk are the seeded flat sketch codecs, "
        "docs/FLAT_DELTA.md §Codec matrix; B in {1,2,4,8}, default 4; "
        "randk reuses --topk-fraction as its keep fraction); "
        "default: topk when -c Y, none otherwise",
    )
    p.add_argument("--topk-fraction", default=0.01, type=float)
    p.add_argument(
        "--codec-policy",
        default="static",
        choices=["static", "adaptive"],
        help="codec selection on the gRPC edge: static = every client uses "
        "--compression every round; adaptive = the coordinator picks a "
        "codec per client per round from observed bytes x RTT "
        "(docs/OPERATIONS.md §Adaptive codec; requires --delta-layout "
        "flat)",
    )
    p.add_argument(
        "--delta-layout",
        default="per_leaf",
        choices=["per_leaf", "flat"],
        help="how client deltas travel through compression/aggregation and "
        "the wire: per_leaf = one codec/reduce dispatch (and one wire "
        "record) per pytree leaf (parity default); flat = pack all leaves "
        "into one lane-aligned [clients, P] buffer per round "
        "(fedtpu.ops.flat) — one top_k / quantize / reduce for the whole "
        "model, ONE contiguous wire record, global top-k budget "
        "(see docs/FLAT_DELTA.md)",
    )


def add_fed_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--rounds", default=20, type=int,
                   help="federated rounds (reference hardcodes 20)")
    p.add_argument("--algorithm", default="fedavg", choices=["fedavg", "fedprox"])
    p.add_argument("--fedprox-mu", default=0.01, type=float)
    p.add_argument(
        "--partition",
        default="round_robin",
        choices=["round_robin", "iid", "dirichlet"],
    )
    p.add_argument("--dirichlet-alpha", default=0.5, type=float)
    add_compression_flags(p)
    p.add_argument(
        "--server-pipeline",
        default="auto",
        choices=["auto", "barrier", "stream"],
        help="how the distributed server consumes StartTrain replies: "
        "barrier = decode into per-leaf host pytrees and stack/transfer/"
        "aggregate after the LAST reply (parity path); stream = decode "
        "each reply into its row of one flat [clients, P] buffer and ship "
        "it to the device as it arrives, leaving a single fused finalize "
        "post-barrier (mean aggregation bit-identical to barrier; "
        "requires --aggregator mean, no DP). auto = stream for "
        "--delta-layout flat when the combination supports it "
        "(see docs/PERF_ANALYSIS.md). Ignored by the simulated engine",
    )
    p.add_argument(
        "--tier-fanout",
        default=0,
        type=int,
        metavar="N",
        help="hierarchical multi-tier aggregation "
        "(docs/ARCHITECTURE.md §Multi-tier): 0 = flat one-tier federation "
        "(default). N >= 1 makes the primary the ROOT of a two-tier "
        "topology whose --clients entries are sub-aggregator addresses "
        "(fedtpu.cli.server --role aggregator), each fronting a cohort of "
        "up to N clients; the root pulls ONE pre-weighted partial sum per "
        "aggregator per round, so its decode+combine work scales with "
        "aggregators, not clients. Requires --delta-layout flat with "
        "--aggregator mean, no DP and no screening; both tiers must agree "
        "on the value",
    )
    p.add_argument(
        "--aggregator",
        default="mean",
        choices=["mean", "median", "trimmed_mean", "krum"],
        help="delta combine rule: mean = (weighted) FedAvg (reference "
        "semantics); median / trimmed_mean = coordinate-wise "
        "Byzantine-robust aggregation; krum = selection-based "
        "(Blanchard et al. 2017)",
    )
    p.add_argument("--trim-fraction", default=0.1, type=float)
    p.add_argument(
        "--server-optimizer",
        default="none",
        choices=["none", "momentum", "adam", "yogi"],
        help="server-side optimizer over the aggregated delta (FedOpt "
        "family): none = FedAvg (reference semantics), momentum = FedAvgM, "
        "adam = FedAdam, yogi = FedYogi",
    )
    p.add_argument("--server-lr", default=1.0, type=float)
    p.add_argument(
        "--unweighted",
        action="store_true",
        help="uniform averaging over active clients instead of "
        "example-count weighting (required for DP)",
    )
    p.add_argument(
        "--dp-clip-norm",
        default=0.0,
        type=float,
        help="DP-FedAvg: clip each client delta to this L2 norm (0 = off; "
        "requires --unweighted, no compression, and a BatchNorm-free model)",
    )
    p.add_argument("--dp-noise-multiplier", default=0.0, type=float)
    p.add_argument(
        "--participation-fraction",
        default=1.0,
        type=float,
        help="random fraction of live clients sampled each round "
        "(1.0 = all, reference behavior)",
    )
    p.add_argument(
        "--participation-sampling",
        default="uniform",
        choices=["uniform", "loss"],
        help="how the sampled subset is drawn: uniform, or importance "
        "sampling proportional to each client's last training loss",
    )
    p.add_argument(
        "--telemetry",
        default="basic",
        choices=["off", "basic", "trace"],
        help="self-measurement level (fedtpu.obs; docs/OBSERVABILITY.md): "
        "off = nothing; basic (default) = thread-safe metrics registry "
        "(RPC bytes, compression ratio, phase times, FT transitions; "
        "dump with --prom-out), <1%% round overhead; trace = basic plus "
        "nested round/client/phase spans exported as Perfetto-loadable "
        "Chrome trace JSON (--trace-out) and bridged to "
        "jax.profiler.TraceAnnotation under --profile-dir",
    )
    add_screening_flags(p)
    add_perf_flags(p)
    p.add_argument(
        "--debug-per-batch",
        action="store_true",
        help="print per-batch loss/acc from inside the jitted local epoch "
        "(the reference's mid-epoch console lines, src/utils.py:51-92). "
        "Host callback per batch — debugging only, ruins throughput",
    )


def add_perf_flags(p: argparse.ArgumentParser) -> None:
    """The perf fast-path bundle (docs/PERF_ANALYSIS.md §Roofline). The
    individual flags default to None so --perf-preset can fill whichever
    ones the user did not set explicitly — an explicit flag always wins
    over the preset."""
    p.add_argument(
        "--compute-dtype",
        default=None,
        choices=["float32", "bfloat16_mixed"],
        help="device compute dtype for local training: float32 = "
        "full-precision parity (default); bfloat16_mixed = bf16 params/"
        "activations/dataset on device with an f32 master copy — "
        "aggregation, FedOpt, screening and checkpoints keep f32 "
        "semantics (measured 2.4x on-chip, "
        "artifacts/BENCH_LIVE_r04_bf16.json)",
    )
    p.add_argument(
        "--megabatch-clients",
        default=None,
        type=int,
        metavar="K",
        help="fold K simulated clients into one [K*batch, F] MXU pass "
        "inside the vmapped round body (must divide the client count; "
        "0 = off). K=1 is bit-identical to the per-client path "
        "(test-pinned); K>1 shares BN batch stats, rng stream and "
        "optimizer trajectory per group (documented approximation) to "
        "raise arithmetic intensity for the small-model zoo",
    )
    p.add_argument(
        "--perf-preset",
        default=None,
        choices=["parity", "fast"],
        help="bundle of perf knobs: parity = float32 + no megabatching "
        "(the bit-parity contract vs the reference); fast = "
        "bfloat16_mixed + the largest of 8/4/2 that divides the client "
        "count. Explicit --compute-dtype/--megabatch-clients always win "
        "over the preset (see docs/PERF_ANALYSIS.md §Roofline)",
    )


def resolve_perf_preset(args, num_clients: int):
    """Resolve --perf-preset + explicit flags to concrete
    (compute_dtype, megabatch_clients) FedConfig values."""
    preset = getattr(args, "perf_preset", None)
    compute = getattr(args, "compute_dtype", None)
    mega = getattr(args, "megabatch_clients", None)
    if preset == "fast":
        if compute is None:
            compute = "bfloat16_mixed"
        if mega is None:
            mega = next(
                (k for k in (8, 4, 2) if num_clients % k == 0), 0
            )
    # "parity" (and no preset) leave the dataclass defaults in charge:
    # float32 + megabatching off.
    return (compute or "float32", 0 if mega is None else mega)


def add_screening_flags(p: argparse.ArgumentParser) -> None:
    """Fused update screening + reputation/quarantine (ScreenConfig;
    docs/FAULT_TOLERANCE.md). All checks default OFF; arming any one turns
    screening on. Composes with --server-pipeline stream and every
    aggregator (unlike median/krum, which are barrier-only)."""
    p.add_argument(
        "--screen-norm",
        default=0.0,
        type=float,
        metavar="L2",
        help="reject client updates whose L2 norm exceeds this absolute "
        "bound (0 = off) — the blunt defense against boosted updates",
    )
    p.add_argument(
        "--screen-z",
        default=0.0,
        type=float,
        metavar="Z",
        help="reject updates whose norm's modified z-score (median/MAD of "
        "the live cohort — robust to the attackers inflating the spread) "
        "exceeds this bound (0 = off; ~3.5 is the textbook outlier cut)",
    )
    p.add_argument(
        "--screen-cos",
        default=-1.0,
        type=float,
        metavar="COS",
        help="reject updates whose cosine against the live cohort's "
        "coordinate-wise median direction falls below this (-1 = off; "
        "0 rejects sign-flipped/contrarian updates)",
    )
    p.add_argument(
        "--quarantine-at",
        default=ScreenConfig.quarantine_at,
        type=float,
        metavar="S",
        help="suspicion EWMA threshold (of per-round screening verdicts) "
        "at which a client is quarantined: still served, updates ignored, "
        "release when suspicion decays below the release threshold",
    )
    p.add_argument(
        "--quarantine-evict-after",
        default=ScreenConfig.evict_after,
        type=int,
        metavar="ROUNDS",
        help="consecutive quarantined rounds before the client is evicted "
        "through the live membership machinery (0 = never auto-evict)",
    )


def screen_config(args) -> ScreenConfig:
    """ScreenConfig from the screening flags (defaults = screening off)."""
    return ScreenConfig(
        norm_max=getattr(args, "screen_norm", 0.0),
        zmax=getattr(args, "screen_z", 0.0),
        cos_min=getattr(args, "screen_cos", -1.0),
        quarantine_at=getattr(
            args, "quarantine_at", ScreenConfig.quarantine_at
        ),
        evict_after=getattr(
            args, "quarantine_evict_after", ScreenConfig.evict_after
        ),
    )


def add_sim_flags(p: argparse.ArgumentParser) -> None:
    """Massive-cohort simulation surface (fedtpu.sim; docs/SIMULATION.md).
    Engine CLI only — the population/cohort split is a property of the
    simulated path (the gRPC topology's population is its real clients)."""
    p.add_argument(
        "--population",
        default=0,
        type=int,
        metavar="N",
        help="simulate N clients total while the device holds only "
        "--cohort of them per round (fedtpu.sim.SimFederation): per-client "
        "dataset assignment + last-seen loss + availability live as host "
        "tables, each round's cohort is gathered into the engine's "
        "fixed-size buffers — device memory O(cohort), not O(population). "
        "0 (default) = resident engine (every client a live device slot)",
    )
    p.add_argument(
        "--cohort",
        default=0,
        type=int,
        metavar="K",
        help="clients per round when --population is set (the engine's "
        "device-buffer size; overrides --num-clients). population == "
        "cohort with uniform sampling reproduces the resident engine "
        "bit-for-bit (test-pinned)",
    )
    p.add_argument(
        "--scenario",
        default="",
        metavar="SPEC",
        help="population heterogeneity scenario (fedtpu.sim.scenario): "
        "base[:k=v,...][+quantity_skew:power=P] with bases iid | "
        "dirichlet:alpha=A | pathological:shards=S | label_skew:classes=C "
        "| quantity_skew:power=P | round_robin. Empty = use --partition "
        "unchanged. Example: 'dirichlet:alpha=0.1+quantity_skew:power=1.5'",
    )
    p.add_argument(
        "--cohort-sampler",
        default="uniform",
        choices=["uniform", "loss"],
        help="how each round's cohort is drawn from the available "
        "population: uniform without replacement, or loss = proportional "
        "to last-seen training loss (never-sampled clients draw at an "
        "optimistic prior, so exploration never starves)",
    )
    p.add_argument(
        "--availability",
        default=1.0,
        type=float,
        metavar="FRACTION",
        help="stationary fraction of the population that is online "
        "(seeded two-state Markov trace; 1.0 = everyone always up)",
    )
    p.add_argument(
        "--churn",
        default=0.0,
        type=float,
        metavar="P",
        help="per-round P(online -> offline) of the availability trace "
        "(P(offline -> online) is derived to keep --availability "
        "stationary); 0 = a frozen availability draw",
    )
    p.add_argument(
        "--loss-prior",
        default=-1.0,
        type=float,
        metavar="LOSS",
        help="optimistic sampling prior for never-sampled clients under "
        "--cohort-sampler loss; negative (default) = the max observed loss",
    )
    p.add_argument(
        "--malicious-fraction",
        default=0.0,
        type=float,
        metavar="FRACTION",
        help="seed this fraction of the simulated population (or of "
        "--num-clients on the resident engine) as Byzantine clients "
        "executing --attack (fedtpu.sim.adversary); attacker identity and "
        "every per-round decision replay bit-identically from the seed",
    )
    p.add_argument(
        "--attack",
        default="sign_flip",
        metavar="SPEC",
        help="what seeded attackers do: kind[:key=val,...] with kinds "
        "sign_flip | scale:factor=F | noise:std=S | label_flip:offset=K "
        "and shared options p= (fire probability), rounds=lo-hi, "
        "collude=1 (one shared draw/noise vector for the whole malicious "
        "set), seed=",
    )


def sim_config(args) -> SimConfig:
    """SimConfig from the sim flags (defaults when a CLI doesn't expose
    them — server/train CLIs build sim-off configs)."""
    return SimConfig(
        population=getattr(args, "population", 0),
        cohort_sampler=getattr(args, "cohort_sampler", "uniform"),
        scenario=getattr(args, "scenario", ""),
        loss_prior=getattr(args, "loss_prior", -1.0),
        availability=getattr(args, "availability", 1.0),
        churn=getattr(args, "churn", 0.0),
        seed=getattr(args, "sim_seed", 0),
        malicious_fraction=getattr(args, "malicious_fraction", 0.0),
        attack=getattr(args, "attack", "sign_flip"),
    )


def add_robustness_flags(p: argparse.ArgumentParser) -> None:
    """Transient-fault resilience + chaos surface (docs/FAULT_TOLERANCE.md),
    shared by all four CLIs. The retry/quorum flags configure the typed
    ``RetryPolicy`` / ``round_quorum`` in FedConfig; ``--chaos-spec`` arms
    the deterministic fault-injection schedule (fedtpu.ft.chaos)."""
    p.add_argument(
        "--chaos-spec",
        default=None,
        metavar="SPEC",
        help="arm deterministic fault injection: JSON "
        '({"seed":7,"rules":[{"kind":"error","rpc":"StartTrain","p":0.3}]}) '
        "or mini-DSL 'kind@rpc:p=0.3,seed=7' with rules joined by ';'. "
        "Kinds: delay|drop|error|corrupt|kill; options p, peer, delay "
        "(seconds), code, rounds=lo-hi, max, seed. Applied via gRPC "
        "interceptors on the server/client CLIs; the RPC-less run/train "
        "CLIs honor delay/kill rules on the pseudo-RPC 'Round'. Every "
        "injection is counted (fedtpu_chaos_injected_total) and flight-"
        "recorded; same spec + seed = same faults (tools/chaos_soak.py)",
    )
    p.add_argument(
        "--rpc-retries",
        default=RetryPolicy.max_attempts,
        type=int,
        metavar="N",
        help="total attempts per RPC before the failure is treated as "
        "real (mark_failed); 1 = the old single-shot behavior. Transient "
        "status codes (UNAVAILABLE, DEADLINE_EXCEEDED, ...) and corrupt "
        "payloads (wire CRC) retry; fatal codes never do",
    )
    p.add_argument(
        "--rpc-backoff",
        default=RetryPolicy.backoff_s,
        type=float,
        metavar="SECONDS",
        help="initial retry backoff; doubles per attempt (jittered, "
        f"capped at {RetryPolicy.backoff_max_s:.1f}s)",
    )
    p.add_argument(
        "--rpc-timeout",
        default=None,
        type=float,
        metavar="SECONDS",
        help="deadline for the data-plane RPCs (StartTrain / SendModel / "
        "FetchModel). Default: the RetryPolicy per-RPC deadlines (600s, "
        "the old hardcoded constant)",
    )
    p.add_argument(
        "--round-quorum",
        default=0.0,
        type=float,
        metavar="FRACTION",
        help="minimum fraction of the round's sampled clients that must "
        "deliver updates for the round to commit; below it the round "
        "aborts with the global model untouched and re-runs. 0 (default) "
        "= aggregate whatever arrived (old behavior)",
    )
    p.add_argument(
        "--backup-ping-timeout",
        default=RetryPolicy.backup_ping_timeout_s,
        type=float,
        metavar="SECONDS",
        help="deadline of the primary's CheckIfPrimaryUp backup ping "
        "(was hardcoded 2.0s)",
    )
    p.add_argument(
        "--heartbeat-period",
        default=FedConfig.ft_heartbeat_period_s,
        type=float,
        metavar="SECONDS",
        help="dead-client re-probe period of the heartbeat monitor "
        "(was hardcoded 1.0s)",
    )
    p.add_argument(
        "--async-poll",
        default=FedConfig.async_poll_s,
        type=float,
        metavar="SECONDS",
        help="reply-queue poll timeout of the async (FedBuff) server loop "
        "(was hardcoded 1.0s)",
    )


def robustness_config(args) -> dict:
    """FedConfig kwargs from the robustness flags (defaults when a CLI
    doesn't expose them)."""
    rpc_timeout = getattr(args, "rpc_timeout", None)
    base = RetryPolicy()
    retry = RetryPolicy(
        max_attempts=getattr(args, "rpc_retries", base.max_attempts),
        backoff_s=getattr(args, "rpc_backoff", base.backoff_s),
        start_train_timeout_s=(
            rpc_timeout if rpc_timeout is not None
            else base.start_train_timeout_s
        ),
        send_model_timeout_s=(
            rpc_timeout if rpc_timeout is not None
            else base.send_model_timeout_s
        ),
        fetch_model_timeout_s=(
            rpc_timeout if rpc_timeout is not None
            else base.fetch_model_timeout_s
        ),
        backup_ping_timeout_s=getattr(
            args, "backup_ping_timeout", base.backup_ping_timeout_s
        ),
    )
    return {
        "retry": retry,
        "round_quorum": getattr(args, "round_quorum", 0.0),
        "ft_watchdog_timeout_s": (
            getattr(args, "watchdog_timeout", None)
            or FedConfig.ft_watchdog_timeout_s
        ),
        "ft_heartbeat_period_s": getattr(
            args, "heartbeat_period", FedConfig.ft_heartbeat_period_s
        ),
        "async_poll_s": getattr(args, "async_poll", FedConfig.async_poll_s),
    }


def add_checkpoint_hardening_flags(p: argparse.ArgumentParser) -> None:
    """Durability knobs shared by the CLIs that own a --checkpoint-dir
    (docs/OPERATIONS.md §Disaster recovery)."""
    p.add_argument(
        "--checkpoint-keep",
        default=3,
        type=int,
        metavar="N",
        help="checkpoint generations retained on disk (pruned only after "
        "the newest verifies). Resume requires >= 2: restore-time "
        "generation fallback needs a previous snapshot to fall back to "
        "when the newest is torn or bit-rotten. <= 0 keeps everything",
    )
    p.add_argument(
        "--checkpoint-sync",
        action="store_true",
        help="write checkpoints synchronously on the round loop instead "
        "of the default background writer thread (the loop then blocks "
        "for encode + fsync + verify each save; the writer path blocks "
        "only for the device->host snapshot — bench.py "
        "--checkpoint-overhead-microbench)",
    )


def make_checkpointer(args, telemetry=None, flight=None, chaos=None):
    """Honor --checkpoint-dir: a hardened Checkpointer (fsync'd atomic
    writes, digest manifests, verify-on-read generation fallback,
    non-fatal saves), wrapped in the BackgroundCheckpointer writer thread
    unless --checkpoint-sync. None when the flag is absent. The caller
    owns ``close()`` (drains the writer so the final generation is durable
    before exit). ``chaos`` arms the seeded ckpt_fail/ckpt_torn/ckpt_rot
    disk faults of --chaos-spec against this store."""
    directory = getattr(args, "checkpoint_dir", None)
    if not directory:
        return None
    from fedtpu.checkpoint import BackgroundCheckpointer, Checkpointer

    inner = Checkpointer(
        directory,
        keep=getattr(args, "checkpoint_keep", 3),
        backend="wire",
        metrics=(
            telemetry.registry
            if telemetry is not None and telemetry.enabled else None
        ),
        flight=flight,
        chaos=chaos,
    )
    if getattr(args, "checkpoint_sync", False):
        return inner
    return BackgroundCheckpointer(inner, telemetry=telemetry)


def make_chaos(args, role: str = ""):
    """Honor --chaos-spec: parse + arm a FaultSchedule (None when absent).
    The armed rules are logged so a soak's transcript names its faults."""
    import logging

    spec = getattr(args, "chaos_spec", None)
    if not spec:
        return None
    from fedtpu.ft import parse_chaos_spec

    chaos = parse_chaos_spec(spec)
    logging.warning(
        "CHAOS ARMED%s: %s", f" ({role})" if role else "", chaos.describe()
    )
    return chaos


def add_telemetry_export_flags(p: argparse.ArgumentParser) -> None:
    """End-of-run exporter paths, shared by the run and server CLIs (the
    per-round JSONL exporter is the existing ``--metrics`` flag)."""
    p.add_argument(
        "--prom-out",
        default=None,
        metavar="PATH",
        help="write the cumulative metrics registry as a Prometheus "
        "text-format dump at exit (the file-shaped /metrics endpoint; "
        "requires --telemetry basic or trace)",
    )
    p.add_argument(
        "--trace-out",
        default=None,
        metavar="PATH",
        help="write the collected spans as Chrome trace-event JSON at exit "
        "(load in Perfetto / chrome://tracing; requires --telemetry trace)",
    )


def export_telemetry(args, telemetry) -> None:
    """Honor --prom-out/--trace-out against a component's Telemetry."""
    import logging

    if getattr(args, "prom_out", None):
        if telemetry.enabled:
            telemetry.export_prometheus(args.prom_out)
        else:
            logging.warning(
                "--prom-out ignored: --telemetry off collects no metrics"
            )
    if getattr(args, "trace_out", None):
        if telemetry.tracing:
            telemetry.export_trace(args.trace_out)
        else:
            logging.warning(
                "--trace-out ignored: spans need --telemetry trace"
            )


def install_final_flush(args, telemetry, metrics=None):
    """Crash-proof the exit-time exporters: --prom-out/--trace-out (and the
    --metrics JSONL close) used to run only on a clean fall-through to the
    CLI's ``finally`` — a SIGTERM (scheduler preemption, ``timeout``,
    ``kill``) bypassed them and lost the whole registry/trace. Registers
    ONE idempotent flush on ``atexit`` + SIGTERM (the handler re-raises
    ``SystemExit`` so the normal ``finally`` path still unwinds), and
    returns it so the CLI's own ``finally`` calls the same function —
    whoever fires first wins, everyone else no-ops.

    Per-record durability needs no handler at all: ``RoundRecordWriter``
    appends + flushes every line, so even SIGKILL keeps all completed
    round records (tested: tests/test_obs_propagation.py kills a run
    mid-flight and parses complete v1 records).
    """
    import atexit
    import logging
    import signal
    import threading

    done = threading.Event()

    def flush() -> None:
        if done.is_set():
            return
        done.set()
        try:
            if metrics is not None:
                metrics.close()
        except Exception:
            logging.exception("final metrics close failed")
        try:
            export_telemetry(args, telemetry)
        except Exception:
            logging.exception("final telemetry export failed")

    atexit.register(flush)

    def _on_term(signum, frame):
        flush()
        raise SystemExit(128 + signum)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread (library/test use); atexit still covers
    return flush


def add_obs_flags(p: argparse.ArgumentParser) -> None:
    """The live introspection plane (fedtpu.obs.http; docs/OBSERVABILITY.md)."""
    p.add_argument(
        "--obs-port",
        default=None,
        type=int,
        metavar="PORT",
        help="serve live introspection HTTP on 127.0.0.1:PORT: /metrics "
        "(Prometheus text from the cumulative registry), /healthz, "
        "/statusz (JSON: round, phase, client liveness, failover role, "
        "last-round phase timings — render live with tools/statusz.py), "
        "/flightz (the crash flight recorder's ring buffer). Off by "
        "default; 0 binds an ephemeral port (logged)",
    )


def add_profile_flags(p: argparse.ArgumentParser) -> None:
    """The performance observatory's capture/accounting controls
    (fedtpu.obs.profile; docs/OBSERVABILITY.md 'Profiling')."""
    p.add_argument(
        "--profile-rounds",
        default=None,
        metavar="N[:M]",
        help="capture a jax.profiler device trace covering rounds [N, M) "
        "(half-open; bare N = that one round) into --profile-trace-dir. "
        "The capture writes a wall-clock sidecar so tools/trace_merge.py "
        "--device-trace aligns device ops with the host span timeline",
    )
    p.add_argument(
        "--profile-trace-dir",
        default="profile_trace",
        metavar="DIR",
        help="output directory for --profile-rounds captures",
    )
    p.add_argument(
        "--mfu",
        default="auto",
        choices=["auto", "off", "analytic", "xla"],
        help="per-round MFU/roofline accounting: fedtpu_mfu_ratio / "
        "achieved-FLOPs/s / step-time gauges + round-record stamps. "
        "'analytic' prices the program by walking its jaxpr (cheap); "
        "'xla' additionally cross-checks against the compiled "
        "executable's cost_analysis (one extra AOT compile at startup); "
        "'auto' = analytic when --telemetry is on, else off",
    )


def resolve_mfu_mode(args) -> str:
    """Collapse --mfu auto against --telemetry: the gauges land in the
    telemetry registry, so accounting without a registry is pure cost."""
    mode = getattr(args, "mfu", "off")
    if mode == "auto":
        return "analytic" if getattr(args, "telemetry", "off") != "off" else "off"
    return mode


def make_capture_window(args, role: str, telemetry=None):
    """Honor --profile-rounds: an armed CaptureWindow, or None. The caller
    drives it with maybe_start(round)/maybe_stop(round) and must stop() it
    at exit (idempotent) so a window open past the last round still closes."""
    spec = getattr(args, "profile_rounds", None)
    if spec is None:
        return None
    from fedtpu.obs.profile import CaptureWindow

    trace_id = None
    if telemetry is not None and telemetry.tracer is not None:
        trace_id = telemetry.tracer.trace_id
    return CaptureWindow(
        spec, getattr(args, "profile_trace_dir", "profile_trace"),
        role=role, trace_id=trace_id,
    )


def install_compile_watcher(telemetry=None, flight=None):
    """Arm the XLA compile observer for a CLI process. Best-effort: an
    already-active watcher (tests driving main() in-process) or a jax
    without the monitoring hook degrades to None, never to a crash."""
    from fedtpu.obs.profile import CompileWatcher

    try:
        return CompileWatcher(telemetry=telemetry, flight=flight).install()
    except Exception:
        import logging

        logging.debug("compile watcher unavailable", exc_info=True)
        return None


def start_obs_server(args, registry=None, status_fn=None, flight=None,
                     health_fn=None):
    """Honor --obs-port: start (and return) the endpoint, or None when the
    flag is absent. The caller owns stop(). ``health_fn`` (() -> (ok,
    reason)) makes /healthz honest — 503 while fenced or quorum is unmet."""
    import logging

    port = getattr(args, "obs_port", None)
    if port is None:
        return None
    from fedtpu.obs import ObsServer

    obs = ObsServer(
        port=port, registry=registry, status_fn=status_fn, flight=flight,
        health_fn=health_fn,
    ).start()
    logging.info(
        "obs endpoint on %s (/metrics /healthz /statusz /flightz)", obs.url
    )
    return obs


def make_flight_recorder(role: str, telemetry=None):
    """One process-wide flight recorder for a CLI entrypoint: ring buffer +
    dump hooks armed (unhandled exception, SIGUSR1), warning+ log capture,
    and — in trace mode — span completions via the tracer sink."""
    from fedtpu.obs import FlightRecorder

    flight = FlightRecorder(role=role).install()
    if telemetry is not None and telemetry.tracer is not None:
        telemetry.tracer.sink = flight.record_span
    return flight


def build_config(args, num_clients: int, steps_per_round: int = 8) -> RoundConfig:
    compress = str(getattr(args, "compressFlag", "N")).upper() == "Y"
    compression = getattr(args, "compression", None)
    rotq_bits = None
    if isinstance(compression, tuple):  # parse_compression (codec, bits)
        compression, rotq_bits = compression
    if compression is None:
        compression = "topk" if compress else "none"
    shape, n_classes = dataset_info(args.dataset)
    compute_dtype, megabatch = resolve_perf_preset(args, num_clients)
    return RoundConfig(
        model=args.model,
        num_classes=n_classes,
        image_size=shape,
        opt=OptimizerConfig(
            learning_rate=args.lr,
            schedule=getattr(args, "schedule", "constant"),
            momentum_dtype=getattr(args, "momentum_dtype", "float32"),
        ),
        data=DataConfig(
            dataset=args.dataset,
            batch_size=args.batch_size,
            eval_batch_size=getattr(args, "eval_batch_size", 100),
            partition=getattr(args, "partition", "round_robin"),
            dirichlet_alpha=getattr(args, "dirichlet_alpha", 0.5),
            seed=args.seed,
            num_examples=args.num_examples,
        ),
        fed=FedConfig(
            num_clients=num_clients,
            num_rounds=getattr(args, "rounds", 20),
            algorithm=getattr(args, "algorithm", "fedavg"),
            fedprox_mu=(
                getattr(args, "fedprox_mu", 0.0)
                if getattr(args, "algorithm", "fedavg") == "fedprox"
                else 0.0
            ),
            compression=compression,
            topk_fraction=getattr(args, "topk_fraction", 0.01),
            rotq_bits=rotq_bits if rotq_bits is not None else 4,
            codec_policy=getattr(args, "codec_policy", "static"),
            delta_layout=getattr(args, "delta_layout", "per_leaf"),
            server_pipeline=getattr(args, "server_pipeline", "auto"),
            aggregator=getattr(args, "aggregator", "mean"),
            trim_fraction=getattr(args, "trim_fraction", 0.1),
            server_optimizer=getattr(args, "server_optimizer", "none"),
            server_lr=getattr(args, "server_lr", 1.0),
            dp_clip_norm=getattr(args, "dp_clip_norm", 0.0),
            dp_noise_multiplier=getattr(args, "dp_noise_multiplier", 0.0),
            weighted=not getattr(args, "unweighted", False),
            participation_fraction=getattr(
                args, "participation_fraction", 1.0
            ),
            participation_sampling=getattr(
                args, "participation_sampling", "uniform"
            ),
            telemetry=getattr(args, "telemetry", "basic"),
            tier_fanout=getattr(args, "tier_fanout", 0),
            compute_dtype=compute_dtype,
            megabatch_clients=megabatch,
            sim=sim_config(args),
            screen=screen_config(args),
            **robustness_config(args),
        ),
        steps_per_round=steps_per_round,
        debug_per_batch=getattr(args, "debug_per_batch", False),
    )


def compress_enabled(args) -> bool:
    return str(getattr(args, "compressFlag", "N")).upper() == "Y"
