"""``python -m fedtpu.cli.train`` — standalone single-node training.

Parity with the reference's original trainer surface (``src/main.py``:
``--lr``, ``-r/--resume``, per-epoch test with best-accuracy checkpointing)
without its import-time side effects. LR schedule defaults to constant —
the reference's effective behavior, since its cosine scheduler is never
stepped (``src/main.py:231-242``); pass ``--schedule cosine`` for the
schedule it intended.
"""

from __future__ import annotations

import argparse
import logging

from fedtpu.cli.common import (
    add_model_flags,
    add_obs_flags,
    add_platform_flag,
    add_robustness_flags,
    apply_platform_flag,
    build_config,
    make_chaos,
    make_flight_recorder,
    start_obs_server,
)
from fedtpu.core.solo import run_solo
from fedtpu.obs import RoundRecordWriter, StatusBoard


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_platform_flag(p)
    add_model_flags(p)
    add_obs_flags(p)
    add_robustness_flags(p)
    p.add_argument("--epochs", default=200, type=int,
                   help="training epochs (reference default: 200)")
    p.add_argument("--checkpoint", default="./checkpoint/solo.fckpt",
                   help="best-accuracy checkpoint path")
    p.add_argument("-r", "--resume", action="store_true")
    p.add_argument("--metrics", default=None, help="JSONL metrics path")
    p.add_argument(
        "--mesh",
        default="off",
        choices=["auto", "off"],
        help="auto: when >1 device is visible and the batch divides evenly, "
        "shard each batch across all devices with pmean'd grads (intra-node "
        "data parallelism — the reference's DataParallel, src/main.py:79-81)",
    )
    args = p.parse_args(argv)
    apply_platform_flag(args)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    cfg = build_config(args, num_clients=1)
    mesh = None
    if args.mesh == "auto":
        import jax

        n_dev = len(jax.devices())
        if n_dev > 1 and cfg.data.batch_size % n_dev == 0:
            from fedtpu.parallel import client_mesh

            mesh = client_mesh(axis_name="batch")
            logging.info("batch axis sharded over %d devices", n_dev)
    # Solo has no Telemetry registry; its /statusz feed is the per-epoch
    # record mirrored onto a StatusBoard by the logger wrapper below.
    status = StatusBoard(role="solo", phase="train", round=0)
    flight = make_flight_recorder("solo")
    obs = start_obs_server(args, status_fn=status.snapshot, flight=flight)
    # Solo has no RPC edge either: chaos delay/kill rules fire once per
    # epoch via the per-epoch logger hook (crash-recovery drills for the
    # best-accuracy checkpoint path).
    chaos = make_chaos(args, role="solo")

    class _StatusLogger(RoundRecordWriter):
        def log(self, step: int, **fields) -> None:
            if chaos is not None:
                chaos.tick_round(step)
            status.update(
                round=step,
                **{k: v for k, v in fields.items()
                   if isinstance(v, (int, float))},
            )
            super().log(step, **fields)

    trainer = run_solo(
        cfg,
        epochs=args.epochs,
        seed=args.seed,
        checkpoint_path=args.checkpoint,
        resume=args.resume,
        logger=_StatusLogger(path=args.metrics),
        mesh=mesh,
    )
    logging.info("best test accuracy: %.4f", trainer.best_acc)
    if obs is not None:
        obs.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
