"""The federated round — one jitted XLA program.

Replaces the reference's entire orchestration layer (``src/server.py:113-179``:
thread-per-client fan-out, blocking unary RPCs, checkpoint files as messages,
host-side key-wise averaging) with:

    vmap(local_update) over the clients axis  →  compress deltas (optional)
    →  masked weighted mean  →  new global model

No host transfer, no serialization, no files. On a mesh, the same round step
runs under ``shard_map`` with the vmap axis sharded and the mean becoming a
``lax.psum`` over ICI (see :mod:`fedtpu.parallel.sharded`).
"""

from __future__ import annotations

import dataclasses
import inspect
import logging
import math
from typing import Any, Callable, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedtpu.config import (
    RoundConfig,
    screening_enabled,
    validate_megabatch,
    validate_screen_config,
)
from fedtpu.core import optim
from fedtpu.core.client import (
    ClientOutput,
    make_local_update,
    make_local_update_mega,
)
from fedtpu.utils import trees

Pytree = Any

log = logging.getLogger("fedtpu.round")

# Aggregators already warned about ignoring example-count weights (warn
# ONCE per process per aggregator — the message is for operators reading a
# startup log, not a per-round nag).
_WEIGHTED_ROBUST_WARNED = set()


def warn_weighted_robust(aggregator: str) -> bool:
    """Robust aggregators deliberately ignore ``weighted=True`` example
    counts (a count-weighted robust statistic would hand adversaries their
    influence back through inflated self-reported counts) — but silently,
    which reads as a bug to an operator who set ``weighted=True``. Say it
    once, loudly; callers also stamp a ``weights_ignored`` flag on round
    records. Returns True when the combination applies."""
    if aggregator == "mean":
        return False
    if aggregator not in _WEIGHTED_ROBUST_WARNED:
        _WEIGHTED_ROBUST_WARNED.add(aggregator)
        log.warning(
            "aggregator=%r ignores example-count weights (weighted=True has "
            "no effect on the combine): robust statistics weight clients "
            "uniformly by design — self-reported counts are an adversary's "
            "influence knob. Set weighted=False to silence this.",
            aggregator,
        )
    return True


class FederatedState(NamedTuple):
    """Persistent cross-round state.

    - ``params`` / ``batch_stats``: the global model (the reference's
      ``optimizedModel.pth``, ``src/server.py:174-179``).
    - ``opt_state``: per-client momentum, stacked on a leading clients axis —
      persists across rounds exactly as each reference client process keeps
      its torch optimizer alive between StartTrain calls (``src/main.py:99``).
    - ``client_rng``: per-client PRNG keys, ``[clients, 2]`` uint32.
    - ``round_idx``: drives the cosine LR schedule.
    - ``comp_state``: per-client compressor residuals (error feedback,
      :mod:`fedtpu.ops.compression`); the empty pytree ``()`` when
      compression or error feedback is off.
    - ``server_opt_state``: server optimizer moments over the global model
      (:mod:`fedtpu.core.server_opt`, the FedOpt family); ``()`` for plain
      FedAvg.
    - ``last_client_loss``: ``[clients]`` f32, each client's most recent
      observed training loss (NaN until first observed; dead/unsampled
      clients keep their previous value). Updated inside the round step —
      so fused scans accumulate it per ROUND on device — and checkpointed
      with the rest of the state. Feeds loss-proportional participation
      sampling (:class:`fedtpu.config.FedConfig`).
    """

    params: Pytree
    batch_stats: Pytree
    opt_state: optim.SGDState
    client_rng: jnp.ndarray
    round_idx: jnp.ndarray
    comp_state: Pytree = ()
    server_opt_state: Pytree = ()
    last_client_loss: jnp.ndarray = ()


class RoundMetrics(NamedTuple):
    """``loss``/``accuracy`` average over ACTIVE clients; ``per_client_loss``
    is the raw ``[clients]`` vector (0 for dead/unsampled clients) — the
    observability hook for spotting a diverging or poisoned client, which
    pairs with the robust aggregators. The reference can only print
    per-batch console lines inside each client process
    (``src/utils.py:51-92``).

    Multi-controller caveat: unlike the replicated scalars,
    ``per_client_loss`` is SHARDED along the mesh's clients axis, so on a
    mesh spanning processes each host can ``np.asarray`` only its local
    slice; use ``jax.experimental.multihost_utils.process_allgather`` to
    fetch the global vector."""

    loss: jnp.ndarray
    accuracy: jnp.ndarray
    num_active: jnp.ndarray
    update_norm: jnp.ndarray
    per_client_loss: jnp.ndarray
    # ``[clients]`` bool: rows REJECTED by the fused screening stage this
    # round (always all-False when screening is off). Sharded like
    # per_client_loss on a mesh.
    screened: jnp.ndarray = ()


class RoundBatch(NamedTuple):
    """One round of input data for all clients, static shapes.

    ``x: [clients, steps, batch, ...]``, ``y: [clients, steps, batch]``,
    ``step_mask: [clients, steps]`` (ragged-shard padding),
    ``weights: [clients]`` (example counts for weighted FedAvg),
    ``alive: [clients]`` (participation mask — the jitted form of the
    reference's heartbeat-maintained ``clients[addr] = True/False`` registry,
    ``src/server.py:59-62,78-101``).
    """

    x: jnp.ndarray
    y: jnp.ndarray
    step_mask: jnp.ndarray
    weights: jnp.ndarray
    alive: jnp.ndarray
    # ``[clients]`` f32/bool attacker-seat mask for the seeded adversarial
    # harness (fedtpu.sim.adversary): 1 = this SEAT currently hosts a
    # malicious client. ``()`` (default) = no attack plumbing — the round
    # step only reads it when the config arms an attack
    # (``sim.malicious_fraction > 0``), so benign programs are unchanged.
    attack_seats: Any = ()


def init_state(
    model: nn.Module,
    cfg: RoundConfig,
    rng: jax.Array,
    sample_input: jnp.ndarray,
    compressor=None,
) -> FederatedState:
    """Initialise global model + per-client state. ``compressor`` (a
    :class:`fedtpu.ops.compression.Compressor`) seeds error-feedback
    residuals when given."""
    init_rng, client_rng = jax.random.split(rng)
    variables = model.init(init_rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if cfg.fed.dp_clip_norm > 0 and jax.tree_util.tree_leaves(batch_stats):
        raise ValueError(
            "DP requires a BatchNorm-free model: batch statistics are "
            "unbounded functions of client data and are released unclipped "
            "and unnoised, voiding the sensitivity bound. Pick a model "
            "without batch_stats (e.g. mlp)."
        )
    n = cfg.fed.num_clients
    # Per-client momentum buffers, stacked along a new leading axis.
    single = optim.init(params, cfg.opt)
    opt_state = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,) + x.shape).copy(), single
    )
    from fedtpu.core import server_opt

    return FederatedState(
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        client_rng=jax.random.split(client_rng, n),
        round_idx=jnp.zeros((), jnp.int32),
        comp_state=() if compressor is None else compressor.init(params, n),
        server_opt_state=server_opt.init(cfg.fed, params),
        last_client_loss=jnp.full((n,), jnp.nan, jnp.float32),
    )


def _robust_over_clients(
    stacked: Pytree,
    alive_w: jnp.ndarray,
    axis_name,
    aggregator: str,
    trim: float,
):
    """Coordinate-wise Byzantine-robust combine over the clients axis.

    ``median``: per-coordinate median of live clients' deltas.
    ``trimmed_mean``: mask coordinates outside the [trim, 1-trim] quantile
    band, then average the survivors (Yin et al. 2018, coordinate-wise).
    Dead/unsampled clients (``alive_w == 0``) are excluded via NaN-masking.
    Example-count weights are deliberately ignored: a robust aggregator that
    weighted by client-reported counts would hand adversaries their
    influence back.

    Under ``shard_map`` the statistic is global per coordinate, so the local
    client slices are first ``all_gather``-ed along the mesh axis — the
    collective rides ICI; the host never participates. This costs one full
    per-client delta tree per device; fine at CNN scale, and the price of a
    true global median (a mean can psum partial sums, a median cannot).
    """
    if aggregator == "trimmed_mean" and trim == 0.0:
        # trim 0 trims nothing: route through the EXACT uniform-mean ops so
        # the result is BIT-IDENTICAL to aggregator='mean' with
        # weighted=False (pinned in tests/test_robust_agg.py) — the
        # quantile-band formulation reduces the same values in a different
        # op order and drifts in the last ulp.
        return _mean_over_clients(
            stacked, (alive_w > 0).astype(jnp.float32), axis_name
        )[0]
    total = jnp.sum(alive_w)
    if axis_name is not None:
        total = jax.lax.psum(total, axis_name)
    alive_any = total > 0

    def leaf(x):
        if axis_name is not None:
            x = jax.lax.all_gather(x, axis_name, axis=0, tiled=True)
            w = jax.lax.all_gather(alive_w, axis_name, axis=0, tiled=True)
        else:
            w = alive_w
        mask = (w > 0).reshape((-1,) + (1,) * (x.ndim - 1))
        xf = x.astype(jnp.float32)
        masked = jnp.where(mask, xf, jnp.nan)
        if aggregator == "median":
            out = jnp.nanmedian(masked, axis=0)
        else:  # trimmed_mean
            # Band bounds snap to actual data points (method lower/higher):
            # an interpolated bound can exclude EVERY value at small client
            # counts (verified at n=2), silently zeroing the update.
            lo = jnp.nanquantile(
                masked, trim, axis=0, keepdims=True, method="lower"
            )
            hi = jnp.nanquantile(
                masked, 1.0 - trim, axis=0, keepdims=True, method="higher"
            )
            band = jnp.where(
                (masked >= lo) & (masked <= hi), masked, jnp.nan
            )
            out = jnp.nanmean(band, axis=0)
        # All-dead round (or a coordinate with no survivors): no update.
        out = jnp.nan_to_num(out, nan=0.0)
        return jnp.where(alive_any, out, 0.0).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


_KRUM_BIG = 1e30  # large-finite "infinity": keeps argmin/sums NaN-free


def _krum_over_clients(
    stacked: Pytree,
    alive_w: jnp.ndarray,
    axis_name,
    trim: float,
):
    """Krum selection (Blanchard et al. 2017): pick the single client whose
    delta has the smallest summed squared distance to its ``n - f - 2``
    nearest neighbors, where ``f = floor(trim * n)`` is the assumed
    Byzantine count. TPU-idiomatic: the pairwise distances are ONE MXU
    matmul (``X @ X.T`` on the flattened ``[clients, params]`` matrix).

    Dead/unsampled clients are excluded from both candidacy and neighbor
    sets (large-finite distance). Degenerate when fewer than ``f + 3``
    clients are live — Krum's own precondition. Under ``shard_map`` the
    flattened deltas are ``all_gather``-ed (same cost/shape as the median
    path's gather).
    """
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    shapes = [l.shape for l in leaves]
    sizes = [math.prod(s[1:]) for s in shapes]
    X = jnp.concatenate(
        [l.reshape(l.shape[0], -1).astype(jnp.float32) for l in leaves], axis=1
    )
    w = alive_w
    if axis_name is not None:
        X = jax.lax.all_gather(X, axis_name, axis=0, tiled=True)
        w = jax.lax.all_gather(w, axis_name, axis=0, tiled=True)
    n = X.shape[0]
    alive = w > 0
    sq = jnp.sum(X * X, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (X @ X.T)
    pair_ok = alive[:, None] & alive[None, :]
    d2 = jnp.where(pair_ok, jnp.maximum(d2, 0.0), _KRUM_BIG)
    d2 = d2 + jnp.eye(n, dtype=d2.dtype) * _KRUM_BIG  # self never a neighbor
    # f and the neighbor count k derive from the LIVE count, not the stacked
    # row count: dead/unsampled rows carry only _KRUM_BIG distances, and a
    # static k > n_live - 1 would pull those into every live score —
    # flattening them all to ~k*1e30 in f32 and degrading argmin to "first
    # live index". k is dynamic, so select via a position mask over the
    # ascending sort instead of a static top_k.
    n_alive = jnp.sum(alive.astype(jnp.int32))
    f_dyn = jnp.floor(trim * n_alive).astype(jnp.int32)
    k_dyn = jnp.maximum(1, n_alive - f_dyn - 2)
    d2_sorted = jnp.sort(d2, axis=1)  # BIG (dead/self) entries sort last
    pos_mask = (jnp.arange(n)[None, :] < k_dyn).astype(d2.dtype)
    scores = jnp.sum(d2_sorted * pos_mask, axis=1)
    scores = jnp.where(alive, scores, jnp.inf)
    sel = jnp.argmin(scores)
    chosen = X[sel]
    alive_any = (jnp.sum(w) > 0).astype(jnp.float32)
    parts = []
    off = 0
    for shape, size in zip(shapes, sizes):
        parts.append(chosen[off : off + size].reshape(shape[1:]))
        off += size
    out_leaves = [
        (p * alive_any).astype(l.dtype) for p, l in zip(parts, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def _dp_clip(stacked: Pytree, clip_norm: float) -> Pytree:
    """Scale each client's delta so its GLOBAL L2 norm (across all leaves)
    is at most ``clip_norm`` (DP-FedAvg per-client sensitivity bound). Each
    client lives wholly on one shard, so no collective is needed."""
    leaves = jax.tree_util.tree_leaves(stacked)
    sq = sum(
        jnp.sum(
            jnp.square(x.astype(jnp.float32)),
            axis=tuple(range(1, x.ndim)),
        )
        for x in leaves
    )
    norm = jnp.sqrt(jnp.maximum(sq, 1e-24))  # [clients]
    scale = jnp.minimum(1.0, clip_norm / norm)
    return jax.tree.map(
        lambda x: (
            x.astype(jnp.float32)
            * scale.reshape((-1,) + (1,) * (x.ndim - 1))
        ).astype(x.dtype),
        stacked,
    )


def _dp_noise(
    tree: Pytree, std: jnp.ndarray, round_idx: jnp.ndarray, seed: int
) -> Pytree:
    """Add seeded Gaussian noise to the aggregated delta. The key depends
    only on (static seed, round) so it is identical on every mesh shard —
    the aggregated delta is replicated and must stay so."""
    base = jax.random.fold_in(jax.random.PRNGKey(seed), round_idx)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(base, len(leaves))
    noised = [
        x + (jax.random.normal(k, x.shape, jnp.float32) * std).astype(x.dtype)
        for x, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def flat_weighted_mean(rows: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """Weighted mean over a ``[clients, P]`` flat-row buffer — the streaming
    server pipeline's post-barrier combine (one fused reduce over rows that
    are already device-resident, shipped row-by-row as replies arrived).

    Same per-coordinate math and the same order-stable stacked axis-0
    reduce as :func:`_mean_over_clients` / ``PrimaryServer._aggregate_impl``
    on the equivalent per-leaf tree, so the result is BIT-IDENTICAL to the
    barrier path's mean (the parity the stream tests pin). A running
    row-by-row accumulator would NOT be: a sequential f32 left fold differs
    from XLA's vectorised reduction in the last ulp on most coordinates
    (measured — see docs/PERF_ANALYSIS.md), which is why the stream path
    keeps the rows and reduces them in one op instead of folding eagerly.
    """
    total = jnp.maximum(jnp.sum(weights), 1e-9)
    w = weights.reshape((-1,) + (1,) * (rows.ndim - 1)).astype(rows.dtype)
    return jnp.sum(rows * w, axis=0) / total.astype(rows.dtype)


def _mean_over_clients(stacked: Pytree, weights: jnp.ndarray, axis_name):
    """Masked weighted mean over the clients axis.

    Without ``axis_name`` this is a plain mean over leading axis 0. Under
    ``shard_map`` the clients axis is sharded across devices, so the local
    weighted sums are combined with ``lax.psum`` over the mesh — the TPU-native
    replacement for the reference's host-side ``allreduce()``
    (``src/server.py:155-179``): the collective rides ICI, the host never sees
    a byte.
    """
    total = jnp.sum(weights)
    if axis_name is not None:
        total = jax.lax.psum(total, axis_name)
    safe = jnp.where(total > 0, total, 1.0)

    def leaf_mean(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        s = jnp.sum(x * w, axis=0)
        if axis_name is not None:
            s = jax.lax.psum(s, axis_name)
        return s / safe.astype(x.dtype)

    mean = jax.tree.map(leaf_mean, stacked)
    # If every client is dead, callers expect "no update": make the mean zero
    # by scaling with [total > 0].
    alive_any = (total > 0).astype(jnp.float32)
    return jax.tree.map(lambda m: m * alive_any.astype(m.dtype), mean), safe


def _megabatch_wrap(mega_v, k: int, stream) -> Callable[..., ClientOutput]:
    """Adapt the group-vmapped megabatch local update to the per-client
    ``vmapped`` call signature, so the rest of the round step (deltas,
    screening, compression, aggregation, metrics) is untouched.

    [clients]-axis inputs are regrouped ``[C] -> [G, k]`` (contiguous in
    client order: clients ``0..k-1`` form group 0), the group body runs
    once per group, and group outputs are broadcast back ``[G] -> [C]``.
    Members that never trained this round (all steps masked: dead client
    or empty shard) keep exactly what the per-client path would give them —
    params/stats fall back to the GLOBAL values (delta exactly 0) and
    opt_state falls back to the member's own pre-round buffers. At k=1
    every reshape/broadcast here is an identity and the wrapped output is
    bit-identical to the per-client path (test-pinned).
    """

    def group(x):
        return x.reshape((x.shape[0] // k, k) + x.shape[1:])

    def wrapped(params, stats, opt_state, *rest):
        if stream:
            images, labels, takes, step_mask, rngs, round_idx = rest
        else:
            xs, ys, step_mask, rngs, round_idx = rest
        n = step_mask.shape[0]
        g = n // k
        # The group optimizer trajectory starts from the mean of its
        # members' buffers (f32 accumulate; a size-1 mean is exact, so k=1
        # parity holds even for bf16-stored momentum).
        def opt_mean(x):
            return jnp.mean(
                group(x).astype(jnp.float32), axis=1
            ).astype(x.dtype)

        opt_g = jax.tree.map(opt_mean, opt_state)
        member_mask = group(step_mask)  # [G, k, steps]
        rng_g = group(rngs)[:, 0]  # member 0's key per group
        if stream == "presharded":
            out = mega_v(
                params, stats, opt_g, group(images), group(labels),
                group(takes), member_mask, rng_g, round_idx,
            )
        elif stream:
            out = mega_v(
                params, stats, opt_g, images, labels,
                group(takes), member_mask, rng_g, round_idx,
            )
        else:
            out = mega_v(
                params, stats, opt_g, group(xs), group(ys),
                member_mask, rng_g, round_idx,
            )
        trained = step_mask.any(axis=1)  # [C]

        def bcast(xg):
            return jnp.broadcast_to(
                xg[:, None], (g, k) + xg.shape[1:]
            ).reshape((n,) + xg.shape[1:])

        def member_where(new, old):
            m = trained.reshape((n,) + (1,) * (new.ndim - 1))
            return jnp.where(m, new, old)

        params_c = jax.tree.map(
            lambda xg, glob: member_where(
                bcast(xg), jnp.broadcast_to(glob[None], (n,) + glob.shape)
            ),
            out.params, params,
        )
        stats_c = jax.tree.map(
            lambda xg, glob: member_where(
                bcast(xg), jnp.broadcast_to(glob[None], (n,) + glob.shape)
            ),
            out.batch_stats, stats,
        )
        opt_c = jax.tree.map(
            lambda xg, old: member_where(bcast(xg), old),
            out.opt_state, opt_state,
        )
        # Per-member metrics come out [G, k] — dead members are already
        # zeroed by the per-example masking, no fallback needed.
        return ClientOutput(
            params=params_c,
            batch_stats=stats_c,
            opt_state=opt_c,
            loss=out.loss.reshape(n),
            accuracy=out.accuracy.reshape(n),
            num_steps=out.num_steps.reshape(n),
        )

    return wrapped


def make_round_step(
    model: nn.Module,
    cfg: RoundConfig,
    compressor=None,  # Optional[fedtpu.ops.compression.Compressor]
    axis_name: Optional[str] = None,
    stream: bool = False,
    image_shape: Optional[Tuple[int, ...]] = None,
) -> Callable[..., Tuple[FederatedState, RoundMetrics]]:
    """Build the round step.

    With ``axis_name=None`` this is the single-program (vmap-only) form. With
    an axis name it is the *per-shard* body to be wrapped in ``shard_map``
    (see :mod:`fedtpu.parallel.sharded`): the vmap then runs over the local
    slice of clients and aggregation becomes ``psum`` collectives.

    ``compressor``, when given, is a stateful delta codec
    (:class:`fedtpu.ops.compression.Compressor`) — the ``-c Y`` parity path;
    its error-feedback residuals ride in ``state.comp_state``.

    With ``stream`` set the returned function is
    ``round_step(state, batch, images, labels)`` and each scan step extracts
    only its own batch, so nothing ``[clients, steps, batch, ...]``-sized is
    ever materialised — see :mod:`fedtpu.data.device`. Two stream forms:
    ``"gather"`` (alias ``True``): ``batch.x`` holds int32 gather indices
    ``[clients, steps, batch]`` into the flat dataset (``batch.y`` ignored);
    ``"presharded"``: ``images``/``labels`` are the per-client
    ``[clients, 2L, ...]`` presharded arrays and ``batch.x`` holds per-step
    slice offsets ``[clients, steps]``.
    """
    from fedtpu.core import server_opt as server_opt_lib

    if stream is True:
        stream = "gather"

    if cfg.fed.delta_layout not in ("per_leaf", "flat"):
        raise ValueError(
            f"unknown delta_layout {cfg.fed.delta_layout!r}; "
            "have per_leaf | flat"
        )
    flat_mode = cfg.fed.delta_layout == "flat"
    # Seeded codecs (rotq/randk) take the round index as their per-round
    # seed, and rotq needs the power-of-two row padding for the Hadamard
    # butterfly — both are static properties of the compressor, resolved
    # once here so the traced body stays branch-free.
    flat_pow2 = compressor is not None and getattr(
        compressor, "pad_pow2", False
    )
    flat_takes_round = (
        compressor is not None
        and compressor.apply_flat is not None
        and "round_idx" in inspect.signature(compressor.apply_flat).parameters
    )
    if compressor is not None:
        comp_layout = getattr(compressor, "layout", "per_leaf")
        if flat_mode and compressor.apply_flat is None:
            raise ValueError(
                "delta_layout='flat' needs a flat-layout compressor "
                "(make_compressor reads FedConfig.delta_layout; or pass "
                "make_topk/make_int8(..., layout='flat'))"
            )
        if not flat_mode and comp_layout == "flat":
            raise ValueError(
                "flat-layout compressor given but "
                "FedConfig.delta_layout='per_leaf' — residual state shapes "
                "would not match; make both agree"
            )
    if cfg.fed.aggregator not in ("mean", "median", "trimmed_mean", "krum"):
        raise ValueError(
            f"unknown aggregator {cfg.fed.aggregator!r}; "
            "have mean | median | trimmed_mean | krum"
        )
    if cfg.fed.weighted:
        warn_weighted_robust(cfg.fed.aggregator)
    # Fused update screening (ScreenConfig; one stats pass over the flat
    # [clients, P] buffer, rejected rows drop out through the agg mask).
    screen = (
        validate_screen_config(cfg.fed.screen)
        if screening_enabled(cfg.fed.screen) else None
    )
    # Seeded adversarial harness (fedtpu.sim.adversary): the attack PLAN is
    # static config; WHICH seats are malicious arrives per round through
    # batch.attack_seats (dynamic under cohort swapping). label_flip acts at
    # the data level (host-side label mutation in the engine) — no delta
    # transform here.
    attack_plan = None
    if cfg.fed.sim.malicious_fraction > 0:
        from fedtpu.sim.adversary import parse_attack

        attack_plan = parse_attack(cfg.fed.sim.attack)
        if attack_plan.kind == "label_flip":
            attack_plan = None
    if cfg.fed.aggregator != "mean":
        if compressor is not None:
            # Top-k deltas are zero outside each client's own top coordinates,
            # so a coordinate-wise median over them is ~0 everywhere — the
            # model would silently stop moving while residuals cycle.
            raise ValueError(
                f"aggregator={cfg.fed.aggregator!r} cannot compose with "
                "delta compression: sparse deltas zero out coordinate-wise "
                "robust statistics. Use compression='none'."
            )
        if not 0.0 <= cfg.fed.trim_fraction < 0.5:
            raise ValueError(
                f"trim_fraction must be in [0, 0.5), got "
                f"{cfg.fed.trim_fraction}"
            )
    if cfg.fed.dp_clip_norm > 0:
        if compressor is not None:
            raise ValueError(
                "DP clipping cannot compose with delta compression: error "
                "feedback re-injects unclipped residual, voiding the "
                "sensitivity bound. Use compression='none'."
            )
        if cfg.fed.weighted:
            raise ValueError(
                "DP requires uniform weighting (FedConfig(weighted=False)): "
                "example-count weights change per-client sensitivity."
            )
        if cfg.fed.aggregator != "mean":
            raise ValueError(
                "DP noise std clip*sigma/n assumes the mean aggregator; "
                f"aggregator={cfg.fed.aggregator!r} has per-client "
                "sensitivity up to ~clip, so the accounting would be "
                "silently invalid. Use aggregator='mean'."
            )
    server_opt = server_opt_lib.make_server_optimizer(cfg.fed)
    local_update = make_local_update(
        model.apply, cfg, stream=stream, image_shape=image_shape
    )
    if stream == "presharded":
        # images/labels are per-client rows — vmapped, unlike the shared
        # flat dataset of the gather form.
        vmapped = jax.vmap(
            local_update,
            in_axes=(None, None, 0, 0, 0, 0, 0, 0, None),
        )
    elif stream:
        vmapped = jax.vmap(
            local_update,
            in_axes=(None, None, 0, None, None, 0, 0, 0, None),
        )
    else:
        vmapped = jax.vmap(
            local_update,
            in_axes=(None, None, 0, 0, 0, 0, 0, None),
        )

    mb = cfg.fed.megabatch_clients
    if mb:
        validate_megabatch(cfg.fed)
        if axis_name is not None:
            raise NotImplementedError(
                "megabatch_clients does not compose with a mesh yet: the "
                "group regrouping is a reshape across the shard_map client "
                "axis. Run megabatched rounds single-chip (the configs it "
                "targets — the small-model zoo — fit one chip)."
            )
        if cfg.debug_per_batch:
            raise ValueError(
                "debug_per_batch prints per-CLIENT batch lines; the "
                "megabatched body trains groups, so the lines would be "
                "misleading. Disable one of the two."
            )
        mega = make_local_update_mega(
            model.apply, cfg, mb, stream=stream, image_shape=image_shape
        )
        if stream == "presharded":
            mega_v = jax.vmap(
                mega, in_axes=(None, None, 0, 0, 0, 0, 0, 0, None)
            )
        elif stream:
            mega_v = jax.vmap(
                mega, in_axes=(None, None, 0, None, None, 0, 0, 0, None)
            )
        else:
            mega_v = jax.vmap(mega, in_axes=(None, None, 0, 0, 0, 0, 0, None))
        vmapped = _megabatch_wrap(mega_v, mb, stream)

    def round_step(
        state: FederatedState,
        batch: RoundBatch,
        images: Optional[jnp.ndarray] = None,
        labels: Optional[jnp.ndarray] = None,
    ) -> Tuple[FederatedState, RoundMetrics]:
        n = batch.alive.shape[0]
        rngs = jax.vmap(jax.random.fold_in)(
            state.client_rng, jnp.broadcast_to(state.round_idx, (n,))
        )
        # Dead clients also get their steps masked out: they do no local work,
        # mirroring a crashed reference client that never receives StartTrain.
        step_mask = batch.step_mask & batch.alive[:, None]
        if stream:
            out: ClientOutput = vmapped(
                state.params,
                state.batch_stats,
                state.opt_state,
                images,
                labels,
                batch.x,
                step_mask,
                rngs,
                state.round_idx,
            )
        else:
            out = vmapped(
                state.params,
                state.batch_stats,
                state.opt_state,
                batch.x,
                batch.y,
                step_mask,
                rngs,
                state.round_idx,
            )

        if cfg.fed.weighted:
            agg_w = batch.weights * batch.alive.astype(batch.weights.dtype)
        else:
            # Uniform over *active* clients — the reference averages uniformly
            # (src/server.py:163-171) but (buggily) includes dead clients'
            # stale files; we deliberately fix that, see SURVEY §"known bugs".
            agg_w = batch.alive.astype(jnp.float32)

        # Aggregate deltas rather than raw params: required for compression
        # and numerically identical to averaging params when uncompressed.
        deltas = jax.tree.map(
            lambda c, g: c - g[None], out.params, state.params
        )
        if flat_mode:
            # Pack ONCE per round into the lane-aligned [clients, P] buffer
            # (fedtpu.ops.flat): compression, error feedback, DP clipping and
            # the aggregation below each become one op over the whole model.
            # A jnp array is itself a pytree, so every downstream combine
            # (mean/median/trimmed_mean/krum, _dp_clip) applies unchanged;
            # per-coordinate math is untouched, which is what keeps
            # compression='none' and 'int8' bit-identical across layouts.
            from fedtpu.ops import flat as flat_ops

            flat_layout = flat_ops.make_layout(state.params, pow2=flat_pow2)
            deltas = flat_ops.pack_stacked(flat_layout, deltas)
        # Model-level adversaries (fedtpu.sim.adversary): malicious seats
        # replace their honest delta with the attacked one BEFORE the codec
        # — the attacker follows the protocol, only its update is hostile.
        # Decisions (round window, per-round fire probability, colluding
        # draws) are pure functions of (plan seed, round_idx) via jax.random
        # — deterministic, so attack runs replay bit-identically from seed.
        atk_fire = None
        if attack_plan is not None and not isinstance(
            batch.attack_seats, tuple
        ):
            from fedtpu.sim.adversary import attack_fire_mask

            atk_fire = attack_fire_mask(
                attack_plan, batch.attack_seats, state.round_idx, n
            )
            coef = jnp.where(
                atk_fire, jnp.float32(attack_plan.coef), jnp.float32(1.0)
            )

            def poison(x):
                c = coef.reshape((-1,) + (1,) * (x.ndim - 1))
                return (x.astype(jnp.float32) * c).astype(x.dtype)

            if attack_plan.coef != 1.0:
                deltas = jax.tree.map(poison, deltas)
            if attack_plan.kind == "noise":
                nkey = jax.random.fold_in(
                    jax.random.PRNGKey(attack_plan.seed ^ 0x4015E5),
                    state.round_idx,
                )
                leaves, treedef = jax.tree_util.tree_flatten(deltas)
                keys = jax.random.split(nkey, max(len(leaves), 1))

                def noisy(x, k):
                    # Colluding mode: ONE shared noise vector for the whole
                    # malicious set (a consistent fake cluster — the attack
                    # that defeats distance-based selection); otherwise
                    # independent per-seat draws.
                    shape = x.shape[1:] if attack_plan.collude else x.shape
                    nz = (
                        jax.random.normal(k, shape, jnp.float32)
                        * attack_plan.std
                    )
                    nz = jnp.broadcast_to(nz, x.shape)
                    m = atk_fire.reshape((-1,) + (1,) * (x.ndim - 1))
                    return jnp.where(
                        m, (x.astype(jnp.float32) + nz).astype(x.dtype), x
                    )

                deltas = jax.tree_util.tree_unflatten(
                    treedef,
                    [noisy(x, k) for x, k in zip(leaves, keys)],
                )
        comp_state = state.comp_state
        if compressor is not None:
            if flat_mode:
                if flat_takes_round:
                    deltas, new_comp = compressor.apply_flat(
                        deltas, comp_state, flat_layout,
                        round_idx=state.round_idx,
                    )
                else:
                    deltas, new_comp = compressor.apply_flat(
                        deltas, comp_state, flat_layout
                    )
            else:
                deltas, new_comp = compressor.apply(deltas, comp_state)
            # Clients contributing nothing this round (agg_w == 0: dead,
            # non-sampled, or zero-weight) must not have their residuals
            # drained either — keep the old residual so the correction is
            # carried until they actually contribute.
            if jax.tree_util.tree_leaves(comp_state):
                keep = agg_w > 0
                comp_state = jax.tree.map(
                    lambda new, old: jnp.where(
                        keep.reshape((-1,) + (1,) * (new.ndim - 1)), new, old
                    ),
                    new_comp,
                    comp_state,
                )
            else:
                comp_state = new_comp
        # BN stats deltas combine with the same rule as params (reference
        # averages the full state_dict, src/server.py:163-171); computed
        # here because krum must select ONE client jointly for both trees.
        stats_delta = jax.tree.map(
            lambda c, g: c - g[None], out.batch_stats, state.batch_stats
        )
        if atk_fire is not None and attack_plan.coef != 1.0:
            # The attacker poisons its WHOLE submission coherently (krum
            # selects params + stats jointly, so a clean stats tree would
            # leak the honest update).
            stats_delta = jax.tree.map(poison, stats_delta)
        # Fused screening: one stats pass over the flat rows; rejected rows
        # leave the combine through the same zero-weight mask dead clients
        # use, so the weighted mean / robust aggregators are untouched
        # bit-cleanly for the survivors.
        screened = jnp.zeros((n,), bool)
        if screen is not None:
            from fedtpu.ops import flat as screen_flat_ops

            rows = (
                deltas if flat_mode
                else screen_flat_ops.pack_stacked(
                    screen_flat_ops.make_layout(state.params), deltas
                )
            )
            keep, _ = screen_flat_ops.screen_rows(
                rows, agg_w, screen.norm_max, screen.zmax, screen.cos_min
            )
            screened = (agg_w > 0) & ~keep
            agg_w = agg_w * keep.astype(agg_w.dtype)
        if cfg.fed.dp_clip_norm > 0:
            deltas = _dp_clip(deltas, cfg.fed.dp_clip_norm)
        if cfg.fed.aggregator == "krum":
            joint = _krum_over_clients(
                {"p": deltas, "s": stats_delta}, agg_w, axis_name,
                cfg.fed.trim_fraction,
            )
            mean_delta, mean_stats_delta = joint["p"], joint["s"]
        else:
            if cfg.fed.aggregator == "mean":
                combine = lambda t: _mean_over_clients(t, agg_w, axis_name)[0]
            else:  # median | trimmed_mean — validated at build time
                combine = lambda t: _robust_over_clients(
                    t, agg_w, axis_name, cfg.fed.aggregator,
                    cfg.fed.trim_fraction,
                )
            mean_delta = combine(deltas)
            mean_stats_delta = combine(stats_delta)
        if flat_mode:
            # Unpack ONCE, on the aggregated [P] row (not per client) —
            # BEFORE DP noise so the per-leaf noise draw is identical to the
            # per-leaf layout's.
            mean_delta = flat_ops.unpack(flat_layout, mean_delta)
        if cfg.fed.dp_clip_norm > 0 and cfg.fed.dp_noise_multiplier > 0:
            n_participants = jnp.sum((agg_w > 0).astype(jnp.float32))
            if axis_name is not None:
                n_participants = jax.lax.psum(n_participants, axis_name)
            std = (
                cfg.fed.dp_clip_norm
                * cfg.fed.dp_noise_multiplier
                / jnp.maximum(n_participants, 1.0)
            )
            mean_delta = _dp_noise(
                mean_delta, std, state.round_idx,
                seed=cfg.data.seed ^ 0x5F5E5F,
            )
        new_params, new_server_opt = server_opt_lib.apply(
            server_opt, state.params, mean_delta, state.server_opt_state
        )
        new_stats = trees.tree_add(state.batch_stats, mean_stats_delta)

        alive_f = batch.alive.astype(jnp.float32)
        loss_sum = jnp.sum(out.loss * alive_f)
        acc_sum = jnp.sum(out.accuracy * alive_f)
        n_alive = jnp.sum(alive_f)
        if axis_name is not None:
            loss_sum = jax.lax.psum(loss_sum, axis_name)
            acc_sum = jax.lax.psum(acc_sum, axis_name)
            n_alive = jax.lax.psum(n_alive, axis_name)
        n_active = jnp.maximum(n_alive, 1.0)
        metrics = RoundMetrics(
            loss=loss_sum / n_active,
            accuracy=acc_sum / n_active,
            num_active=n_alive,
            update_norm=trees.tree_norm(mean_delta),
            per_client_loss=out.loss * alive_f,
            screened=screened,
        )
        new_state = FederatedState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=out.opt_state,
            client_rng=state.client_rng,
            round_idx=state.round_idx + 1,
            comp_state=comp_state,
            server_opt_state=new_server_opt,
            # Observe only clients that actually TRAINED this round: an
            # alive client with an empty shard runs zero steps and its
            # out.loss is a masked artifact (0.0) — recording it would hand
            # loss-proportional sampling a stale zero that starves the
            # client forever. Never-trained clients keep NaN and draw at
            # the optimistic prior instead (fedtpu.sim.sampling).
            last_client_loss=jnp.where(
                step_mask.any(axis=1),
                out.loss.astype(jnp.float32),
                state.last_client_loss,
            ),
        )
        return new_state, metrics

    return round_step
