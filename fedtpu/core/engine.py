"""High-level federated training engine.

The user-facing replacement for the reference's ``run()`` orchestration
(``src/server.py:113-153``): builds model + data + round step from a
:class:`fedtpu.config.RoundConfig`, then drives rounds. Each round is one
jitted call. The dataset and client-assignment matrix live in HBM
(:mod:`fedtpu.data.device`): per-round batch gathering happens inside the
jitted program, so the host contributes only the tiny ``alive`` mask per
round — no per-round host data rebuild, no bulk H2D transfer.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Callable, Optional, Tuple

if TYPE_CHECKING:
    from fedtpu.ops.compression import Compressor

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu import models as model_zoo
from fedtpu.config import RoundConfig, resolve_compute_dtype, validate_megabatch
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    init_state,
    make_round_step,
)
from fedtpu.core.client import make_eval_fn
from fedtpu.data import data_source, dataset_info, load, partition
from fedtpu.obs import StatusBoard, Telemetry, validate_telemetry_mode
from fedtpu.utils.metrics import MetricsLogger

# NOTE: fedtpu.data.device imports from fedtpu.core.round, whose package
# __init__ imports this module — so every data.device import below is
# deferred to call time to keep the package import-order insensitive.


class Federation:
    """Synchronous federated training over simulated clients on one program.

    Capabilities map (reference → here):
      - client registry + ranks (``src/server.py:281-282,126-129``) →
        the ``clients`` array axis; ``alive`` mask ↔ heartbeat status.
      - StartTrain fan-out / join barrier (``src/server.py:124-135``) →
        ``vmap`` inside one jitted round step.
      - ``allreduce()`` checkpoint averaging (``src/server.py:155-179``) →
        on-device masked weighted mean.
    """

    def __init__(
        self,
        cfg: RoundConfig,
        seed: int = 0,
        compressor: Optional["Compressor"] = None,
        data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        mesh=None,
        assignment: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ):
        """``mesh``: an optional ``jax.sharding.Mesh`` over a ``clients``
        axis — rounds then run under ``shard_map`` with per-client state and
        data sharded across its devices and FedAvg as a psum over ICI
        (:mod:`fedtpu.parallel`). ``None`` keeps the single-program path
        (one chip, or tests).

        ``assignment``: an externally-built ``(idx, mask)`` client→example
        map (``[num_clients, shard_len]``, the :mod:`fedtpu.data.partition`
        convention) used instead of partitioning internally — the hook the
        massive-cohort simulation layer (:mod:`fedtpu.sim`) uses to hand the
        engine a cohort's rows gathered from a much larger population."""
        self.cfg = cfg
        self.mesh = mesh
        # Config validation FIRST — a bad flag must not cost a model build,
        # a dataset load, jit construction, or even backend initialisation
        # (enable_compile_cache touches the backend; on the wedge-prone
        # tunnel that is a potential hang point) before raising.
        if cfg.fed.participation_sampling not in ("uniform", "loss"):
            raise ValueError(
                f"unknown participation_sampling "
                f"{cfg.fed.participation_sampling!r}; have uniform | loss"
            )
        if cfg.data.device_layout not in ("presharded", "gather"):
            raise ValueError(
                f"unknown device_layout {cfg.data.device_layout!r}; "
                "have presharded | gather"
            )
        if cfg.opt.momentum_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown momentum_dtype {cfg.opt.momentum_dtype!r}; "
                "have float32 | bfloat16"
            )
        if cfg.fed.delta_layout not in ("per_leaf", "flat"):
            raise ValueError(
                f"unknown delta_layout {cfg.fed.delta_layout!r}; "
                "have per_leaf | flat"
            )
        if not 0.0 <= cfg.fed.sim.malicious_fraction < 1.0:
            raise ValueError(
                f"sim.malicious_fraction must be in [0, 1), got "
                f"{cfg.fed.sim.malicious_fraction}"
            )
        resolve_compute_dtype(cfg)  # raises on an unknown compute_dtype
        validate_megabatch(cfg.fed)
        if mesh is not None and cfg.fed.megabatch_clients:
            raise NotImplementedError(
                "megabatch_clients does not compose with a mesh yet: the "
                "group regrouping is a reshape across the shard_map client "
                "axis. Run megabatched rounds single-chip."
            )
        validate_telemetry_mode(cfg.fed.telemetry)
        shape, n_classes = dataset_info(cfg.data.dataset)
        if cfg.num_classes != n_classes:
            raise ValueError(
                f"cfg.num_classes={cfg.num_classes} but dataset "
                f"'{cfg.data.dataset}' has {n_classes} classes — set "
                f"RoundConfig(num_classes={n_classes})"
            )
        # Persistent XLA compile cache: on the wedge-prone remote-tunnel TPU
        # a large program's compile can outlive the tunnel window that
        # started it; caching at the engine layer covers every entrypoint
        # (bench tools, CLIs, harnesses) without a per-script checklist.
        # Deliberately AFTER the cheap validation above: it initialises the
        # JAX backend, which an invalid config must never pay for.
        from fedtpu.utils.platform import enable_compile_cache

        enable_compile_cache()
        if cfg.fed.compression != "none" and compressor is None:
            from fedtpu.ops.compression import make_compressor

            compressor = make_compressor(cfg.fed)
        # local_epochs folds into the per-round step count: one epoch is
        # steps_per_round passes over the shard (make_client_batches wraps
        # short shards), matching the reference's epochs-per-StartTrain knob.
        self._steps = cfg.steps_per_round * max(1, cfg.fed.local_epochs)
        self.model = model_zoo.create(
            cfg.model, num_classes=cfg.num_classes, remat=cfg.remat
        )

        if data is None:
            images, labels = load(
                cfg.data.dataset,
                "train",
                seed=cfg.data.seed,
                num=cfg.data.num_examples,
            )
            # Captured immediately after OUR load so an unrelated later load
            # of the same dataset name can't relabel this run.
            self._data_source = data_source(cfg.data.dataset, "train")
        else:
            images, labels = data
            self._data_source = "caller"
        self.images, self.labels = images, labels

        n = cfg.fed.num_clients
        if assignment is not None:
            idx, mask = np.asarray(assignment[0]), np.asarray(assignment[1])
            if idx.shape[0] != n or idx.shape != mask.shape:
                raise ValueError(
                    f"assignment must be [num_clients={n}, shard_len] "
                    f"idx/mask pairs, got {idx.shape} vs {mask.shape}"
                )
        elif cfg.data.partition == "round_robin":
            idx, mask = partition.round_robin(len(images), n, cfg.data.batch_size)
        elif cfg.data.partition == "iid":
            idx, mask = partition.iid(len(images), n, seed=cfg.data.seed)
        elif cfg.data.partition == "dirichlet":
            idx, mask = partition.dirichlet(
                labels, n, alpha=cfg.data.dirichlet_alpha, seed=cfg.data.seed
            )
        else:
            raise ValueError(f"unknown partition {cfg.data.partition}")
        self.client_idx, self.client_mask = idx, mask
        self.weights = jnp.asarray(partition.shard_sizes(mask))

        # Seeded adversarial participants (fedtpu.sim.adversary; the
        # SimConfig.malicious_fraction axis). On the resident engine the
        # seat IS the client, so the attacker mask is static; SimFederation
        # re-derives the per-seat mask from each round's cohort ids.
        # label_flip is a DATA attack: the attackers' example labels are
        # poisoned here on the host and the jitted program is unchanged.
        self._attack_plan = None
        self._attack_seats = None
        if cfg.fed.sim.malicious_fraction > 0:
            from fedtpu.sim import adversary

            plan = adversary.parse_attack(cfg.fed.sim.attack)
            self._attack_plan = plan
            if mesh is not None:
                raise NotImplementedError(
                    "sim.malicious_fraction does not compose with a mesh "
                    "yet (the attack mask is not threaded through "
                    "shard_map); run the adversarial scenario single-chip"
                )
            if cfg.fed.sim.population <= 0:
                amask = adversary.attacker_mask(
                    n, cfg.fed.sim.malicious_fraction,
                    cfg.data.seed + cfg.fed.sim.seed + plan.seed,
                )
                self.attacker_clients = amask
                if plan.kind == "label_flip":
                    # Static data poisoning: p/rounds windows do not apply
                    # (the shard is poisoned for the whole run).
                    labels = adversary.flip_labels(
                        labels, idx, mask, amask, plan.label_offset,
                        cfg.num_classes,
                    )
                    self.labels = labels
                else:
                    self._attack_seats = amask.astype(np.float32)

        sample = jnp.zeros((1,) + tuple(images.shape[1:]), jnp.float32)
        self.state: FederatedState = init_state(
            self.model, cfg, jax.random.PRNGKey(seed), sample, compressor
        )
        shuffle = cfg.data.partition != "round_robin"
        img_shape = tuple(images.shape[1:])
        layout = cfg.data.device_layout
        if layout == "presharded":
            # Footprint guard: presharded costs clients * 2L floats of
            # labels-side rows where L is the padded MAX shard length, so a
            # skewed partition (low-alpha dirichlet) can inflate far beyond
            # the 2x-dataset cost of the balanced case. Fall back to the
            # gather layout (correct for every shape, just slower on TPU)
            # rather than OOM.
            footprint = 2 * n * idx.shape[1]
            if footprint > 4 * len(images):
                import warnings

                warnings.warn(
                    f"device_layout='presharded' would store "
                    f"{footprint / len(images):.1f}x the dataset (skewed "
                    f"partition: max shard {idx.shape[1]} of {len(images)} "
                    f"examples x {n} clients); falling back to 'gather'",
                    stacklevel=2,
                )
                layout = "gather"
        self._layout = layout
        if mesh is None:
            from fedtpu.data.device import make_data_round_step

            self._round_step = jax.jit(
                make_round_step(self.model, cfg, compressor), donate_argnums=(0,)
            )
            self._data_step = jax.jit(
                make_data_round_step(
                    self.model, cfg, self._steps, compressor, shuffle=shuffle,
                    image_shape=img_shape, layout=layout,
                ),
                donate_argnums=(0,),
            )
        else:
            from fedtpu.data.device import make_sharded_data_round_step
            from fedtpu.parallel.sharded import make_sharded_round_step

            self._round_step = make_sharded_round_step(
                self.model, cfg, mesh, compressor
            )
            self._data_step = make_sharded_data_round_step(
                self.model, cfg, self._steps, mesh, compressor, shuffle=shuffle,
                image_shape=img_shape, layout=layout,
            )
            # self.state was already mesh-placed by the property setter.
            self.weights = self._placed(self.weights, sharded=True)
        # Device-resident data (uploaded lazily on the first device-path
        # step, so explicit-batch callers never pay the HBM footprint):
        # dataset + assignment matrix go to HBM once; each round gathers its
        # batches inside the jitted step.
        self._device_data = None
        self._data_key = jax.random.PRNGKey(cfg.data.seed)
        self._evaluate = make_eval_fn(self.model.apply, cfg)
        self.alive = np.ones((n,), bool)
        self._compressor = compressor
        self._shuffle = shuffle
        self._img_shape = img_shape
        self._multi_steps = {}  # num_rounds -> compiled scan program
        # Host-side telemetry (fedtpu.obs): spans wrap the per-round
        # DISPATCH walls (device compute is async; use profile_rounds /
        # the trace-mode jax bridge for on-device time), counters track
        # rounds completed. Swappable post-construction — the jitted
        # programs never close over it (bench.py --telemetry-microbench
        # retimes one engine under all three modes).
        self.telemetry = Telemetry(cfg.fed.telemetry, role="engine")
        # Live status feed (fedtpu.obs.http: /statusz via --obs-port):
        # round/phase updates are one locked dict merge each — cheap enough
        # to run unconditionally (bench.py --obs-plane-microbench).
        self.status = StatusBoard(
            role="engine", phase="init", round=0,
            num_clients=cfg.fed.num_clients,
        )
        # Continuous MFU/roofline accounting (fedtpu.obs.profile): OPT-IN
        # via enable_mfu_accounting() — building the cost model traces and
        # AOT-compiles the round program once (seconds), which library
        # users constructing many engines must not pay implicitly. The
        # per-round observe is a few gauge sets (bench.py --mfu-microbench
        # gates it ≤1% of a round).
        self.profiler = None
        # Optional process-wide CompileWatcher, attached by the owning CLI
        # (jax.monitoring listeners are global, so the process owns it, not
        # the engine) — surfaced on /statusz when present.
        self.compile_watcher = None

    def enable_mfu_accounting(self, xla_check: bool = True):
        """Arm per-round MFU/roofline gauges + round-record stamping.

        Builds the per-round cost model now (analytic jaxpr FLOP walk,
        cross-checked against XLA ``cost_analysis`` when ``xla_check``) —
        a one-time trace/compile cost, so this is explicit rather than a
        construction default. Returns the :class:`RoundProfiler`."""
        from fedtpu.obs.profile import RoundProfiler, engine_cost_model

        if self.profiler is None:
            if self.mesh is not None:
                n_dev = len(self.mesh.devices.flatten())
                kind = self.mesh.devices.flatten()[0].device_kind
            else:
                n_dev = 1
                kind = jax.devices()[0].device_kind
            self.profiler = RoundProfiler(
                self.telemetry, n_devices=n_dev, device_kind=kind,
            )
            self.profiler.set_cost_model(
                engine_cost_model(self, xla_check=xla_check)
            )
        return self.profiler

    def status_snapshot(self) -> dict:
        """``/statusz`` feed: live round/phase plus the alive mask (and the
        perf/compile observability blocks when armed)."""
        snap = self.status.snapshot()
        snap["alive"] = self.alive.tolist()
        if self.telemetry.tracer is not None:
            snap["trace_id"] = self.telemetry.tracer.trace_id
        if self.profiler is not None:
            snap["perf"] = self.profiler.snapshot()
        if self.compile_watcher is not None:
            snap["compile"] = self.compile_watcher.snapshot()
        return snap

    def _placed(self, x, sharded: bool):
        """Place an array for the active topology: sharded along the clients
        axis (or replicated) on the mesh, or a plain device_put without one."""
        if self.mesh is None:
            return jax.device_put(jnp.asarray(x))
        from fedtpu.parallel.sharded import _put
        from jax.sharding import PartitionSpec as P

        return _put(x, self.mesh, P(self.cfg.mesh_axis) if sharded else P())

    def _store_dtype(self):
        """HBM storage dtype for the device-resident images: the COMPUTE
        dtype. Every consumer (the client local step) casts inputs to the
        compute dtype as its first act, so storing bf16 under a bf16 config
        is bit-identical end-to-end while halving the dataset's HBM
        footprint and every per-round slice/gather's bandwidth."""
        import ml_dtypes

        dt = jnp.dtype(resolve_compute_dtype(self.cfg))
        return np.dtype(ml_dtypes.bfloat16) if dt == jnp.bfloat16 else np.float32

    def _ensure_device_data(self):
        if self._device_data is None:
            store = self._store_dtype()
            if self._layout == "presharded":
                # Per-client contiguous rows ([n, 2L, F], see
                # fedtpu.data.device.preshard_arrays) — sharded by CLIENT on
                # a mesh, so each device stores only its own clients' data.
                from fedtpu.data.device import preshard_arrays

                xs_c, ys_c = preshard_arrays(
                    self.images, self.labels, self.client_idx,
                    self.client_mask,
                )
                self._device_data = (
                    self._placed(xs_c.astype(store), sharded=True),
                    self._placed(ys_c, sharded=True),
                    self._placed(self.client_idx, sharded=True),
                    self._placed(self.client_mask, sharded=True),
                )
                return self._device_data
            # Gather layout: dataset replicated (every device gathers its own
            # clients' batches locally); assignment matrix sharded by client.
            # Images live FLAT ([N, H*W*C]): NHWC tensors pad ~4x under TPU
            # tiled layouts, flat rows tile exactly — the per-batch reshape
            # after the gather is free.
            flat = np.asarray(self.images, np.float32).reshape(
                len(self.images), -1
            ).astype(store)
            self._device_data = (
                self._placed(flat, sharded=False),
                self._placed(np.asarray(self.labels, np.int32), sharded=False),
                self._placed(self.client_idx, sharded=True),
                self._placed(self.client_mask, sharded=True),
            )
        return self._device_data

    # ---------------------------------------------------------------- data
    def set_assignment(
        self,
        idx: np.ndarray,
        mask: np.ndarray,
        weights: Optional[np.ndarray] = None,
    ) -> None:
        """Swap the client→example assignment in place (same shapes).

        The sim layer's per-round cohort re-gather: the jitted data-round
        program takes ``idx``/``mask`` as *inputs* of static shape, so
        replacing their VALUES (a cohort-sized H2D of int32 rows) swaps
        which population clients the fixed device slots represent without
        recompiling. Gather layout only — the presharded layout bakes the
        assignment into per-client data rows at upload, which would cost an
        O(cohort·shard·features) re-preshard per round.
        """
        if self._layout != "gather":
            raise ValueError(
                "set_assignment requires device_layout='gather' (presharded "
                "bakes the assignment into the uploaded data rows)"
            )
        idx = np.asarray(idx, np.int32)
        mask = np.asarray(mask, bool)
        if idx.shape != self.client_idx.shape or mask.shape != idx.shape:
            raise ValueError(
                f"assignment shape {idx.shape} must match the engine's "
                f"{self.client_idx.shape} (static program shapes)"
            )
        self.client_idx, self.client_mask = idx, mask
        w = partition.shard_sizes(mask) if weights is None else weights
        self.weights = self._placed(np.asarray(w, np.float32),
                                    sharded=self.mesh is not None)
        if self._device_data is not None:
            d_images, d_labels, _, _ = self._device_data
            self._device_data = (
                d_images,
                d_labels,
                self._placed(idx, sharded=True),
                self._placed(mask, sharded=True),
            )

    def _alive_for_round(self, round_idx: int) -> np.ndarray:
        """This round's participation mask: heartbeat-dead clients plus
        optional subsampling of the live ones (the reference always uses
        every live client). With ``participation_sampling='loss'`` the
        subset is drawn with probability proportional to each client's last
        observed training loss (importance sampling — worst-served clients
        get picked more often); uniform until a loss has been observed, and
        a fused block reuses the losses known before the block started."""
        alive = self.alive.copy()
        frac = self.cfg.fed.participation_fraction
        if frac < 1.0:
            rng = np.random.default_rng(self.cfg.data.seed * 7919 + round_idx)
            live = np.flatnonzero(alive)
            k = max(1, int(round(frac * len(live))))
            p = None
            if self.cfg.fed.participation_sampling == "loss":
                # Observations live in FederatedState (updated per round on
                # device, NaN until first observed, checkpointed); fetched
                # only here, when a sampling decision actually needs them.
                # Multi-controller: the loss vector is SHARDED by client
                # across processes, so every controller allgathers the full
                # vector first — identical inputs + the round-seeded
                # deterministic draw below then yield the SAME mask on every
                # host (the desync hazard that previously made this
                # single-controller only). Tested by a real two-process run
                # (tests/test_multihost.py).
                loss_vec = self._state.last_client_loss
                if not getattr(loss_vec, "is_fully_addressable", True):
                    # Mesh spanning processes: allgather yields the global
                    # [N] vector on every host. Gate on addressability, NOT
                    # process_count: a host-local vector under an initialized
                    # cluster (mesh=None — independent federations per host)
                    # is already complete, and tiled concatenation would
                    # silently hand every host process 0's copy.
                    from jax.experimental import multihost_utils

                    loss_vec = multihost_utils.process_allgather(
                        loss_vec, tiled=True
                    )
                # Shared sparse-observation rule (fedtpu.sim.sampling):
                # never-observed clients draw at the optimistic fill (max
                # observed loss) so they are explored, not starved; None
                # (nothing observed yet) falls back to uniform. The sim
                # layer's population-scale cohort sampler routes through
                # the SAME function, so both surfaces weigh sparse
                # last-seen losses identically.
                from fedtpu.sim.sampling import loss_weights

                p = loss_weights(np.asarray(loss_vec)[live])
            keep = rng.choice(live, size=k, replace=False, p=p)
            alive = np.zeros_like(alive)
            alive[keep] = True
        return alive

    def round_batch(self, round_idx: int) -> RoundBatch:
        """Materialise this round's batch tensors on the HOST.

        Kept for tests and for callers that inject custom batches; the hot
        path (:meth:`step` with ``batch=None``) gathers on device instead and
        never calls this.
        """
        cfg = self.cfg
        x, y, step_mask = partition.make_client_batches(
            self.images,
            self.labels,
            self.client_idx,
            self.client_mask,
            cfg.data.batch_size,
            self._steps,
            seed=cfg.data.seed + round_idx,
            shuffle=cfg.data.partition != "round_robin",
        )
        return RoundBatch(
            x=jnp.asarray(x),
            y=jnp.asarray(y),
            step_mask=jnp.asarray(step_mask),
            weights=self.weights,
            alive=jnp.asarray(self._alive_for_round(round_idx)),
            attack_seats=(
                jnp.asarray(self._attack_seats)
                if self._attack_seats is not None else ()
            ),
        )

    @property
    def data_source(self) -> str:
        """'disk' | 'synthetic' | 'caller' — where this instance's training
        data came from (captured at construction)."""
        return self._data_source

    # --------------------------------------------------------------- rounds
    @property
    def state(self) -> FederatedState:
        return self._state

    @state.setter
    def state(self, s: FederatedState) -> None:
        # External assignment (e.g. checkpoint resume) invalidates the
        # host-side round counter; it re-syncs from the device on next use.
        # On a mesh, host/numpy trees (a restored checkpoint) are placed with
        # the engine's shardings so resume Just Works; trees that already
        # hold non-addressable global arrays (multi-controller stepping
        # output) are left untouched.
        if self.mesh is not None:
            leaves = jax.tree_util.tree_leaves(s)
            already_global = any(
                isinstance(l, jax.Array) and not l.is_fully_addressable
                for l in leaves
            )
            if not already_global:
                from fedtpu.parallel.sharded import shard_state

                s = shard_state(s, self.mesh, self.cfg.mesh_axis)
        self._state = s
        self._round_host = None

    def _round_number(self) -> int:
        """Host-tracked current round. Avoids a blocking device readback of
        ``state.round_idx`` every round (which would serialise dispatch
        against the previous round's compute)."""
        if self._round_host is None:
            self._round_host = int(self._state.round_idx)
        return self._round_host

    def step(self, batch: Optional[RoundBatch] = None) -> RoundMetrics:
        tel = self.telemetry
        r = self._round_number()
        self.status.update(round=r, phase="round")
        t0 = time.perf_counter()
        with tel.span("round", round=r):
            metrics = self._step_impl(batch)
        if self.profiler is not None:
            self.profiler.observe_round(time.perf_counter() - t0)
        self.status.update(round=r + 1, phase="idle")
        tel.counter(
            "fedtpu_rounds_completed_total",
            "simulated FedAvg rounds dispatched by this engine",
        ).inc()
        return metrics

    def _step_impl(self, batch: Optional[RoundBatch] = None) -> RoundMetrics:
        r = self._round_number()
        if batch is not None:
            if self.mesh is not None:
                from fedtpu.parallel.sharded import shard_batch

                batch = shard_batch(batch, self.mesh, self.cfg.mesh_axis)
            self._state, metrics = self._round_step(self._state, batch)
            self._round_host = r + 1
            return metrics
        d_images, d_labels, d_idx, d_mask = self._ensure_device_data()
        extra = (
            (jnp.asarray(self._attack_seats),)
            if self._attack_seats is not None else ()
        )
        self._state, metrics = self._data_step(
            self._state,
            d_images,
            d_labels,
            d_idx,
            d_mask,
            self.weights,
            self._placed(self._alive_for_round(r), sharded=True),
            self._data_key,
            *extra,
        )
        self._round_host = r + 1
        return metrics

    def _multi_step(self, num_rounds: int):
        """Build (and cache) the ``num_rounds``-round fused scan program."""
        if num_rounds not in self._multi_steps:
            if self.mesh is None:
                from fedtpu.data.device import make_multi_round_step

                self._multi_steps[num_rounds] = jax.jit(
                    make_multi_round_step(
                        self.model, self.cfg, self._steps, num_rounds,
                        self._compressor, shuffle=self._shuffle,
                        image_shape=self._img_shape, layout=self._layout,
                    ),
                    donate_argnums=(0,),
                )
            else:
                from fedtpu.data.device import make_sharded_multi_round_step

                self._multi_steps[num_rounds] = make_sharded_multi_round_step(
                    self.model, self.cfg, self._steps, num_rounds, self.mesh,
                    self._compressor, shuffle=self._shuffle,
                    image_shape=self._img_shape, layout=self._layout,
                )
        return self._multi_steps[num_rounds]

    def run_on_device(self, num_rounds: int) -> RoundMetrics:
        """Run ``num_rounds`` rounds as ONE fused XLA program (``lax.scan``).

        Numerically identical to ``num_rounds`` calls of :meth:`step` (the
        per-round shuffle key folds ``round_idx``, and per-round alive masks
        — heartbeat state + participation sampling — are precomputed on the
        host and scanned over), but with zero host involvement between
        rounds: no dispatch, no sync, no data movement. This is the
        framework's answer to the reference's per-round host round-trip
        (``src/server.py:120-153``) taken to its limit; on a remote/tunneled
        device it also amortises dispatch latency across the whole run.
        Returns metrics stacked ``[num_rounds, ...]``.
        """
        if num_rounds < 1:
            raise ValueError(f"num_rounds must be >= 1, got {num_rounds}")
        tel = self.telemetry
        r = self._round_number()
        self.status.update(round=r, phase="fused_rounds",
                           fused_block=num_rounds)
        t0 = time.perf_counter()
        with tel.span("fused_rounds", round=r, num_rounds=num_rounds):
            alive = np.stack(
                [self._alive_for_round(r + i) for i in range(num_rounds)]
            )
            d_images, d_labels, d_idx, d_mask = self._ensure_device_data()
            if self.mesh is None:
                alive_dev = jnp.asarray(alive)
            else:
                from fedtpu.parallel.sharded import _put
                from jax.sharding import PartitionSpec as P

                alive_dev = _put(alive, self.mesh, P(None, self.cfg.mesh_axis))
            extra = (
                (jnp.asarray(self._attack_seats),)
                if self._attack_seats is not None else ()
            )
            self._state, metrics = self._multi_step(num_rounds)(
                self._state,
                d_images,
                d_labels,
                d_idx,
                d_mask,
                self.weights,
                alive_dev,
                self._data_key,
                *extra,
            )
        if self.profiler is not None:
            # The fused dispatch is async; the stacked metrics fetch by the
            # CALLER is the honest sync point, so this wall is dispatch
            # latency on a device backend. CLI loops that fetch inside the
            # block (fedtpu.cli.run does) get true per-round walls.
            self.profiler.observe_round(
                time.perf_counter() - t0, rounds=num_rounds
            )
        self._round_host = r + num_rounds
        self.status.update(round=r + num_rounds, phase="idle")
        tel.counter(
            "fedtpu_rounds_completed_total",
            "simulated FedAvg rounds dispatched by this engine",
        ).inc(num_rounds)
        return metrics

    def run(
        self,
        num_rounds: Optional[int] = None,
        logger: Optional[MetricsLogger] = None,
        eval_every: int = 0,
        eval_data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> RoundMetrics:
        if num_rounds is None:
            num_rounds = self.cfg.fed.num_rounds
        from fedtpu.config import screening_enabled

        metrics = None
        self.eval_history = []
        screen_on = screening_enabled(self.cfg.fed.screen)
        for r in range(num_rounds):
            t0 = time.time()
            ridx = self._round_number()
            metrics = self.step()
            rec = {
                "loss": metrics.loss,
                "acc": metrics.accuracy,
                "active": metrics.num_active,
                # Worst live client this round — a diverging/poisoned client
                # shows up here rounds before it drags the mean.
                "worst_client_loss": float(
                    jnp.max(metrics.per_client_loss)
                ),
                "round_s": time.time() - t0,
                "dataset": self.cfg.data.dataset,
                # 'synthetic' when the loader fell back — accuracy curves from
                # such runs must never be read as real-data results. Captured
                # at construction from THIS instance's load (or 'caller' for
                # injected data), immune to later unrelated loads.
                "data_source": self._data_source,
            }
            self.telemetry.histogram(
                "fedtpu_round_wall_seconds",
                "per-round host wall time (dispatch + sync)",
            ).observe(rec["round_s"])
            if self.profiler is not None:
                # step() already observed this round into the gauges; the
                # record stamps the SAME last-round figures (absent when the
                # cost model or the peak table can't derive them — e.g.
                # unknown device kind without FEDTPU_PEAK_FLOPS).
                rec.update(self.profiler.record_fields())
            if screen_on:
                # The run() loop already syncs per round (worst_client_loss
                # above), so reading the verdict mask costs nothing extra.
                n_screened = int(np.sum(np.asarray(metrics.screened)))
                rec["screened"] = n_screened
                if n_screened:
                    self.telemetry.counter(
                        "fedtpu_screening_rejected_total",
                        "client rows rejected by the fused screening "
                        "stage, by surface",
                        labels={"surface": "engine"},
                    ).inc(n_screened)
            if self._attack_plan is not None:
                from fedtpu.sim import adversary

                if self._attack_seats is not None:
                    fired = adversary.fires_this_round(
                        self._attack_plan, self._attack_seats, ridx
                    )
                    n_fired = int(fired.sum())
                else:  # label_flip: statically poisoned shards train every round
                    n_fired = int(
                        getattr(self, "attacker_clients",
                                np.zeros(0, bool)).sum()
                    )
                rec["attackers_fired"] = n_fired
                if n_fired:
                    self.telemetry.counter(
                        "fedtpu_attack_injected_total",
                        "model/data-level attacks executed by seeded "
                        "adversarial clients, by kind",
                        labels={"kind": self._attack_plan.kind},
                    ).inc(n_fired)
            if eval_every and (r + 1) % eval_every == 0 and eval_data is not None:
                te_loss, te_acc = self.evaluate(*eval_data)
                rec["test_loss"], rec["test_acc"] = te_loss, te_acc
                self.eval_history.append((r, te_loss, te_acc))
            if logger is not None:
                logger.log(r, **rec)
        return metrics

    # ----------------------------------------------------------------- eval
    def evaluate(self, images: np.ndarray, labels: np.ndarray):
        """Evaluate the current global model (parity: ``src/main.py:167-191``)."""
        from fedtpu.core.client import batch_eval_arrays

        xs, ys = batch_eval_arrays(images, labels, self.cfg.data.eval_batch_size)
        loss, acc = self._evaluate(self.state.params, self.state.batch_stats, xs, ys)
        return float(loss), float(acc)

    # ------------------------------------------------------- fault injection
    def set_alive(self, client: int, alive: bool) -> None:
        """Mark a simulated client dead/alive (the reference flips
        ``clients[addr]`` on RpcError / heartbeat success,
        ``src/server.py:59-62,95-99``)."""
        self.alive[client] = alive
