"""High-level federated training engine.

The user-facing replacement for the reference's ``run()`` orchestration
(``src/server.py:113-153``): builds model + data + round step from a
:class:`fedtpu.config.RoundConfig`, then drives rounds. Each round is one
jitted call; data for the round is prepared on the host (static-shape batch
tensors) and donated to the device.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Callable, Optional, Tuple

if TYPE_CHECKING:
    from fedtpu.ops.compression import Compressor

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu import models as model_zoo
from fedtpu.config import RoundConfig
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    init_state,
    make_round_step,
)
from fedtpu.core.client import make_eval_fn
from fedtpu.data import dataset_info, load, partition
from fedtpu.utils.metrics import MetricsLogger


class Federation:
    """Synchronous federated training over simulated clients on one program.

    Capabilities map (reference → here):
      - client registry + ranks (``src/server.py:281-282,126-129``) →
        the ``clients`` array axis; ``alive`` mask ↔ heartbeat status.
      - StartTrain fan-out / join barrier (``src/server.py:124-135``) →
        ``vmap`` inside one jitted round step.
      - ``allreduce()`` checkpoint averaging (``src/server.py:155-179``) →
        on-device masked weighted mean.
    """

    def __init__(
        self,
        cfg: RoundConfig,
        seed: int = 0,
        compressor: Optional["Compressor"] = None,
        data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ):
        self.cfg = cfg
        shape, n_classes = dataset_info(cfg.data.dataset)
        if cfg.num_classes != n_classes:
            raise ValueError(
                f"cfg.num_classes={cfg.num_classes} but dataset "
                f"'{cfg.data.dataset}' has {n_classes} classes — set "
                f"RoundConfig(num_classes={n_classes})"
            )
        if cfg.fed.compression != "none" and compressor is None:
            from fedtpu.ops.compression import make_compressor

            compressor = make_compressor(cfg.fed)
        # local_epochs folds into the per-round step count: one epoch is
        # steps_per_round passes over the shard (make_client_batches wraps
        # short shards), matching the reference's epochs-per-StartTrain knob.
        self._steps = cfg.steps_per_round * max(1, cfg.fed.local_epochs)
        self.model = model_zoo.create(cfg.model, num_classes=cfg.num_classes)

        if data is None:
            images, labels = load(
                cfg.data.dataset,
                "train",
                seed=cfg.data.seed,
                num=cfg.data.num_examples,
            )
        else:
            images, labels = data
        self.images, self.labels = images, labels

        n = cfg.fed.num_clients
        if cfg.data.partition == "round_robin":
            idx, mask = partition.round_robin(len(images), n, cfg.data.batch_size)
        elif cfg.data.partition == "iid":
            idx, mask = partition.iid(len(images), n, seed=cfg.data.seed)
        elif cfg.data.partition == "dirichlet":
            idx, mask = partition.dirichlet(
                labels, n, alpha=cfg.data.dirichlet_alpha, seed=cfg.data.seed
            )
        else:
            raise ValueError(f"unknown partition {cfg.data.partition}")
        self.client_idx, self.client_mask = idx, mask
        self.weights = jnp.asarray(partition.shard_sizes(mask))

        sample = jnp.zeros((1,) + tuple(images.shape[1:]), jnp.float32)
        self.state: FederatedState = init_state(
            self.model, cfg, jax.random.PRNGKey(seed), sample, compressor
        )
        self._round_step = jax.jit(
            make_round_step(self.model, cfg, compressor), donate_argnums=(0,)
        )
        self._evaluate = make_eval_fn(self.model.apply, cfg)
        self.alive = np.ones((n,), bool)

    # ---------------------------------------------------------------- data
    def round_batch(self, round_idx: int) -> RoundBatch:
        """Materialise this round's static-shape batch tensors."""
        cfg = self.cfg
        x, y, step_mask = partition.make_client_batches(
            self.images,
            self.labels,
            self.client_idx,
            self.client_mask,
            cfg.data.batch_size,
            self._steps,
            seed=cfg.data.seed + round_idx,
            shuffle=cfg.data.partition != "round_robin",
        )
        alive = self.alive.copy()
        frac = cfg.fed.participation_fraction
        if frac < 1.0:
            # Client sampling: each round a random fraction of the *live*
            # clients participates (standard FL subsampling; the reference
            # always uses every live client).
            rng = np.random.default_rng(cfg.data.seed * 7919 + round_idx)
            live = np.flatnonzero(alive)
            k = max(1, int(round(frac * len(live))))
            keep = rng.choice(live, size=k, replace=False)
            alive = np.zeros_like(alive)
            alive[keep] = True
        return RoundBatch(
            x=jnp.asarray(x),
            y=jnp.asarray(y),
            step_mask=jnp.asarray(step_mask),
            weights=self.weights,
            alive=jnp.asarray(alive),
        )

    # --------------------------------------------------------------- rounds
    def step(self, batch: Optional[RoundBatch] = None) -> RoundMetrics:
        r = int(self.state.round_idx)
        if batch is None:
            batch = self.round_batch(r)
        self.state, metrics = self._round_step(self.state, batch)
        return metrics

    def run(
        self,
        num_rounds: Optional[int] = None,
        logger: Optional[MetricsLogger] = None,
        eval_every: int = 0,
        eval_data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> RoundMetrics:
        if num_rounds is None:
            num_rounds = self.cfg.fed.num_rounds
        metrics = None
        self.eval_history = []
        for r in range(num_rounds):
            t0 = time.time()
            metrics = self.step()
            rec = {
                "loss": metrics.loss,
                "acc": metrics.accuracy,
                "active": metrics.num_active,
                "round_s": time.time() - t0,
            }
            if eval_every and (r + 1) % eval_every == 0 and eval_data is not None:
                te_loss, te_acc = self.evaluate(*eval_data)
                rec["test_loss"], rec["test_acc"] = te_loss, te_acc
                self.eval_history.append((r, te_loss, te_acc))
            if logger is not None:
                logger.log(r, **rec)
        return metrics

    # ----------------------------------------------------------------- eval
    def evaluate(self, images: np.ndarray, labels: np.ndarray):
        """Evaluate the current global model (parity: ``src/main.py:167-191``)."""
        from fedtpu.core.client import batch_eval_arrays

        xs, ys = batch_eval_arrays(images, labels, self.cfg.data.eval_batch_size)
        loss, acc = self._evaluate(self.state.params, self.state.batch_stats, xs, ys)
        return float(loss), float(acc)

    # ------------------------------------------------------- fault injection
    def set_alive(self, client: int, alive: bool) -> None:
        """Mark a simulated client dead/alive (the reference flips
        ``clients[addr]`` on RpcError / heartbeat success,
        ``src/server.py:59-62,95-99``)."""
        self.alive[client] = alive
