"""Engine-side semi-asynchronous FedBuff — buffered aggregation ON DEVICE.

VERDICT r3 "Next round" #7: ``PrimaryServer.run_async`` gives the gRPC edge
FedBuff semantics (clients train continuously, the server aggregates every K
replies with staleness-discounted weights), but the simulated engine had no
async mode, so async federated learning could not be studied at 64-client
scale on a chip. This module is that study tool: the same buffered,
staleness-weighted aggregation expressed as one jitted XLA program over the
simulated client axis.

Discretized-time semantics (documented, deliberate): one engine *tick* is
one wall-clock unit. A live client that has not yet trained since its last
pull trains ONE local epoch on its own model copy this tick (``vmap`` over
per-client parameters — unlike the synchronous round step, clients here
genuinely hold diverged models), then holds that pending update until it
*arrives*. An *arrival schedule* — [ticks, clients] boolean masks with
``buffer_k`` true per tick, host-chosen — decides which clients report each
tick. An arriving client contributes ``local_params - its_pull_snapshot``
(exactly one local epoch computed against a possibly-stale base — the
FedBuff client cycle: pull, train once, submit; NOT a compounding open-ended
trajectory), combined as ``sum(disc_i * w_i * delta_i) / sum(w_i)`` with
``disc = (1 + staleness)**-staleness_power`` and ``w = examples`` (or 1
unweighted), where staleness counts server updates since its pull — the
discount scales the applied MAGNITUDE (FedBuff, Nguyen et al. 2022; see
:func:`fedbuff_combine` for the round-4 normalized alternative and the
measured reason damping is the default). Same rule as ``run_async``,
:mod:`fedtpu.transport.federation`, whose gRPC clients likewise train one
cycle per pull. After aggregation the arrivals re-pull the fresh global
model and train anew next tick; clients awaiting arrival idle. No barrier
anywhere: the reference's join-on-slowest (``src/server.py:132-135``)
simply has no counterpart here.

Composition limits mirror ``run_async`` and are rejected at build time:
mean aggregator only (a K-sized buffer is too small a population for robust
statistics), no delta compression (sparse deltas against stale baselines
corrupt aggregation), no DP (per-update participation accounting differs
from the synchronous analysis).

Mesh mode (``AsyncFederation(mesh=...)``, VERDICT r4 #6): ticks run under
``shard_map`` over the clients axis. Async's per-client DIVERGED model
copies shard exactly like presharded data rows — each device holds
``3 * params * clients_per_device`` of trajectory state (local + pull
snapshot + momentum; the sync engine holds 1x, momentum only) — and the
buffer aggregation + scalar metrics become psums over ICI. Sharded ==
single-program parity is pinned in ``tests/test_async_engine.py``.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import RoundConfig
from fedtpu.core import optim
from fedtpu.core.client import ClientOutput, make_local_update
from fedtpu.core.round import _mean_over_clients
from fedtpu.utils import trees

log = logging.getLogger(__name__)

Pytree = Any


class AsyncState(NamedTuple):
    """Device-resident state of the asynchronous federation.

    Per-client model copies are first-class here (``client_*``): async
    clients genuinely train diverged models, unlike the synchronous
    :class:`fedtpu.core.round.FederatedState` where every client starts each
    round from the shared global. ``base_*`` snapshots what each client
    pulled (delta baseline); ``base_version`` when it pulled it.
    """

    params: Pytree            # global model
    batch_stats: Pytree
    client_params: Pytree     # [clients, ...] local trajectories
    client_stats: Pytree
    base_params: Pytree       # [clients, ...] pull snapshots
    base_stats: Pytree
    opt_state: optim.SGDState  # [clients, ...] per-client momentum
    client_rng: jnp.ndarray
    base_version: jnp.ndarray  # [clients] int32
    version: jnp.ndarray       # scalar int32: server updates so far
    # True = this client has trained its one epoch since its last pull and
    # is holding the update until it arrives (it idles meanwhile).
    pending: jnp.ndarray = ()
    server_opt_state: Pytree = ()
    last_client_loss: jnp.ndarray = ()


class AsyncMetrics(NamedTuple):
    """Per-tick observability. ``loss``/``accuracy`` average over clients
    that trained this tick; ``staleness_mean`` is over this tick's
    ARRIVALS (the FedBuff-specific signal: how discounted the buffer was)."""

    loss: jnp.ndarray
    accuracy: jnp.ndarray
    num_arrived: jnp.ndarray
    staleness_mean: jnp.ndarray
    update_norm: jnp.ndarray
    per_client_loss: jnp.ndarray


def fedbuff_combine(
    stacked: Pytree,
    raw_w: jnp.ndarray,
    staleness: jnp.ndarray,
    staleness_power: float,
    axis_name: Optional[str] = None,
    staleness_damping: bool = True,
):
    """Combine a buffer of per-client contributions, FedBuff-style.

    ``raw_w``: [clients] pre-discount weights, already zero for
    non-arrivals. Damping (default): ``sum(disc*w*x) / sum(w)`` — the
    staleness discount ``disc = (1+s)^-p`` scales the applied MAGNITUDE
    (Nguyen et al. 2022). ``staleness_damping=False``: the weight-
    normalized mean ``/ sum(disc*w)``, where any uniform discount cancels
    (the round-4 semantics; see :func:`make_async_step` for the measured
    consequences). Under ``shard_map`` the reductions psum over
    ``axis_name``. Property-pinned in ``tests/test_properties.py``.
    """
    agg_w = raw_w / (1.0 + staleness) ** staleness_power
    mean = _mean_over_clients(stacked, agg_w, axis_name)[0]
    if not staleness_damping:
        return mean

    def allsum(x):
        s = jnp.sum(x)
        return jax.lax.psum(s, axis_name) if axis_name is not None else s

    damp = allsum(agg_w) / jnp.maximum(allsum(raw_w), 1e-9)
    return jax.tree.map(lambda d: d * damp, mean)


def _validate(cfg: RoundConfig) -> None:
    if cfg.fed.compression != "none":
        raise ValueError(
            "async engine requires compression='none': sparse deltas "
            "against stale baselines corrupt aggregation."
        )
    if cfg.fed.aggregator != "mean":
        raise ValueError(
            "async engine requires aggregator='mean': a buffer_k-sized "
            "buffer is too small a population for robust statistics."
        )
    if cfg.fed.dp_clip_norm > 0:
        raise ValueError(
            "async engine does not support DP: per-update participation "
            "accounting differs from the synchronous analysis."
        )
    if cfg.fed.algorithm not in ("fedavg", "fedprox"):
        raise ValueError(f"unknown algorithm {cfg.fed.algorithm!r}")


def init_async_state(
    model, cfg: RoundConfig, rng: jax.Array, sample: jnp.ndarray, mesh=None
) -> AsyncState:
    """Start everyone synced at version 0 (the distributed edge's
    ``sync_clients`` before the first update).

    With ``mesh`` EVERY ``[clients, ...]`` stack — the trajectory copies
    (``client_*``/``base_*``), the momentum buffers, and the small
    per-client vectors — is built inside one jit with sharded
    ``out_shardings``, from nothing bigger than ONE global model copy: the
    broadcasts partition across devices, so no device ever materialises a
    full replicated per-client stack and populations whose
    ``3 * params * clients`` exceeds one device's HBM (the very case the
    mesh exists for) init without an OOM on device 0.

    Value parity: the RNG splits mirror :func:`fedtpu.core.round.init_state`
    exactly (init key -> model.init, client key -> per-client split), so
    mesh and single-program inits are the same federation.
    """
    from fedtpu.core import server_opt

    init_rng, client_rng = jax.random.split(rng)
    variables = model.init(init_rng, sample, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    n = cfg.fed.num_clients
    mom_dtype = optim._momentum_dtype(cfg.opt)

    def build(params, batch_stats, client_key):
        def rep(tree):
            return jax.tree.map(
                lambda x: jnp.broadcast_to(x, (n,) + x.shape), tree
            )

        return AsyncState(
            params=params,
            batch_stats=batch_stats,
            client_params=rep(params),
            client_stats=rep(batch_stats),
            base_params=rep(params),
            base_stats=rep(batch_stats),
            opt_state=optim.SGDState(momentum=jax.tree.map(
                lambda p: jnp.zeros((n,) + p.shape, mom_dtype), params)),
            client_rng=jax.random.split(client_key, n),
            base_version=jnp.zeros((n,), jnp.int32),
            version=jnp.zeros((), jnp.int32),
            pending=jnp.zeros((n,), jnp.bool_),
            server_opt_state=server_opt.init(cfg.fed, params),
            last_client_loss=jnp.full((n,), jnp.nan, jnp.float32),
        )

    if mesh is None:
        return jax.jit(build)(params, batch_stats, client_rng)
    from jax.sharding import NamedSharding

    from fedtpu.parallel.sharded import async_state_specs

    specs = async_state_specs(cfg.mesh_axis)
    out_shardings = type(specs)(
        *(NamedSharding(mesh, getattr(specs, f)) for f in specs._fields)
    )
    return jax.jit(build, out_shardings=out_shardings)(
        params, batch_stats, client_rng
    )


def make_async_step(
    model,
    cfg: RoundConfig,
    steps: int,
    staleness_power: float = 0.5,
    shuffle: bool = True,
    image_shape: Optional[Tuple[int, ...]] = None,
    layout: str = "presharded",
    axis_name: Optional[str] = None,
    staleness_damping: bool = True,
) -> Callable[..., Tuple[AsyncState, AsyncMetrics]]:
    """One tick: every live client trains ``steps`` batches on its OWN
    model; arriving clients' accumulated deltas aggregate into the global.

    ``step(state, images, labels, idx, mask, weights, arrive, alive,
    data_key)`` with ``arrive``/``alive``: [clients] bool,
    ``arrive & ~alive`` forbidden (host schedules arrivals among the live).

    With ``axis_name`` this is the PER-SHARD body for
    :func:`fedtpu.parallel.sharded.make_sharded_async_step`: the clients
    axis of every per-client array is a mesh shard, the buffer aggregation
    and the scalar metrics reduce with ``lax.psum`` over the axis (exactly
    the sync round's collective pattern — per-client diverged model copies
    shard like presharded data rows, so async costs no cross-device traffic
    beyond the same delta all-reduce).

    ``staleness_damping`` (default True — the FedBuff-paper semantics,
    Nguyen et al. 2022): the staleness discount scales the MAGNITUDE of the
    applied update (``sum(disc_i * w_i * delta_i) / sum(w_i)``), not just
    the relative mix. The alternative (False) is the round-4 semantics: a
    weight-NORMALIZED mean (``/ sum(disc_i * w_i)``), where any uniform
    discount cancels — measured consequence (round-5 sweep,
    ``ASYNC_SYNC_CONVERGENCE.jsonl``): with homogeneous speeds (sigma=0,
    k=2) buffer arrivals usually share one staleness value, the discount
    cancels every tick, full-magnitude stale updates keep kicking the model
    around, and smallcnn/cifar10_hard stalls at chance for 30+ ticks while
    sigma=1 (mixed-staleness buffers, where relative weighting does bite)
    converges. Damping restores the paper's magnitude-scaling.

    Measured limits of damping (the full round-5 sweep, `*_damped` rows):
    damping alone does NOT rescue the homogeneous-speed stall — neither
    sp=0.5 (final 0.14) nor the strong sp=2 point (0.11) — because that
    stall is ultimately SMALL-BUFFER VARIANCE: k-of-n aggregation applies
    n/k times more updates per epoch-equivalent, each a k-sample mean, and
    no staleness treatment (relative or magnitude) shrinks the variance of
    FRESH arrivals. What recovers it is the step-size levers: client lr
    0.05 -> 0.01 (0.50 vs 0.09 at tick 15) or server_lr ~ k/n (0.30,
    climbing) — matching the FedBuff paper's tuned-server-lr practice.
    Operational guidance: with homogeneous client speeds and k << n, scale
    ``FedConfig(server_lr=...)`` toward k/n (or reduce client lr); damping
    stays the right default because it bounds the staleness-amplification
    error at negligible cost in the healthy heterogeneous regime (sigma=1:
    0.59 damped vs 0.72 undamped at tick 25, both still climbing).
    """
    from fedtpu.core import server_opt as server_opt_lib

    _validate(cfg)
    server_opt = server_opt_lib.make_server_optimizer(cfg.fed)
    local_update = make_local_update(
        model.apply, cfg, stream=False, image_shape=image_shape
    )
    # Unlike the synchronous round (params broadcast, in_axes=None), every
    # client carries ITS OWN params/stats — the defining feature of async.
    # The FedProx proximal anchor is passed SEPARATELY (the client's last
    # pulled global): the scan starts from the diverged local trajectory,
    # and anchoring mu there would make it a per-tick no-op.
    vmapped = jax.vmap(
        local_update, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0)
    )
    batch_size = cfg.data.batch_size
    need = steps * batch_size
    shape = tuple(image_shape or cfg.image_size)

    def step(
        state: AsyncState,
        images: jnp.ndarray,
        labels: jnp.ndarray,
        idx: jnp.ndarray,
        mask: jnp.ndarray,
        weights: jnp.ndarray,
        arrive: jnp.ndarray,
        alive: jnp.ndarray,
        data_key: jax.Array,
    ) -> Tuple[AsyncState, AsyncMetrics]:
        n = idx.shape[0]
        rng = (
            jax.random.fold_in(data_key, state.version) if shuffle else None
        )
        if rng is not None and axis_name is not None and layout == "gather":
            # Decorrelate per-client shard permutations across mesh shards
            # (mirrors make_data_round_step): the per-shard body sees only
            # its local [clients/shards, L] rows, so without the axis fold
            # every device would draw byte-identical permutation keys and
            # clients c, c+n/shards, ... would shuffle in lockstep. The
            # presharded rotation offset stays deliberately UNfolded — it is
            # a shared scalar, which is what keeps mesh == single-program
            # bit-parity there.
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
        if layout == "presharded":
            # Contiguous rotated slice of the per-client rows (see
            # fedtpu.data.device: the gather below was measured to dominate
            # the fused tick on TPU, artifacts/MFU_PROFILE_r04.json).
            from fedtpu.data.device import _round_offset, presharded_window

            off, _ = _round_offset(labels, shuffle, rng)
            x, y = presharded_window(
                images, labels, off, steps, batch_size, shape
            )
        else:
            # Deferred import: fedtpu.data.device itself imports from
            # fedtpu.core.round, so a module-level import here makes the
            # package import-order sensitive (data.device first -> cycle).
            from fedtpu.data.device import round_take_indices

            take = round_take_indices(idx, mask, need, rng)
            tail = shape if images.ndim == 2 else tuple(images.shape[1:])
            x = images[take].reshape((n, steps, batch_size) + tail)
            y = labels[take].reshape((n, steps, batch_size))
        has_data = mask.any(axis=1)
        # One epoch per pull cycle (the FedBuff client loop): a client that
        # already holds a pending update idles until it arrives — masked
        # steps are no-ops, so its params/momentum stay frozen.
        trains = has_data & alive & ~state.pending
        step_mask = jnp.broadcast_to(trains[:, None], (n, steps))
        rngs = jax.vmap(jax.random.fold_in)(
            state.client_rng, jnp.broadcast_to(state.version, (n,))
        )
        out: ClientOutput = vmapped(
            state.client_params,
            state.client_stats,
            state.opt_state,
            x,
            y,
            step_mask,
            rngs,
            state.version,
            state.base_params,
        )

        # FedBuff weights over this tick's arrivals only.
        staleness = (state.version - state.base_version).astype(jnp.float32)
        if cfg.fed.weighted:
            base_w = weights.astype(jnp.float32)
        else:
            base_w = jnp.ones((n,), jnp.float32)
        raw_w = base_w * arrive.astype(jnp.float32)
        deltas = jax.tree.map(
            lambda c, b: c - b, out.params, state.base_params
        )
        stats_delta = jax.tree.map(
            lambda c, b: c - b, out.batch_stats, state.base_stats
        )
        combine = lambda tree: fedbuff_combine(  # noqa: E731
            tree, raw_w, staleness, staleness_power,
            axis_name=axis_name, staleness_damping=staleness_damping,
        )
        mean_delta = combine(deltas)
        mean_stats_delta = combine(stats_delta)

        def allsum(x):
            s = jnp.sum(x)
            return jax.lax.psum(s, axis_name) if axis_name is not None else s
        new_params, new_server_opt = server_opt_lib.apply(
            server_opt, state.params, mean_delta, state.server_opt_state
        )
        new_stats = trees.tree_add(state.batch_stats, mean_stats_delta)
        new_version = state.version + 1

        # Arrivals re-pull the fresh global; everyone else trains on.
        def pull(cl, glob):
            sel = arrive.reshape((-1,) + (1,) * (cl.ndim - 1))
            return jnp.where(sel, glob[None], cl)

        new_client_params = jax.tree.map(
            pull, out.params, new_params
        )
        new_client_stats = jax.tree.map(
            pull, out.batch_stats, new_stats
        )
        new_base_params = jax.tree.map(
            pull, state.base_params, new_params
        )
        new_base_stats = jax.tree.map(
            pull, state.base_stats, new_stats
        )
        # Scalar metrics reduce over ALL clients; under shard_map each term
        # is a per-shard partial that psums over the mesh axis (allsum).
        arrived_f = arrive.astype(jnp.float32)
        n_arrived = allsum(arrived_f)
        trains_f = trains.astype(jnp.float32)
        n_trained = jnp.maximum(allsum(trains_f), 1.0)
        metrics = AsyncMetrics(
            loss=allsum(out.loss * trains_f) / n_trained,
            accuracy=allsum(out.accuracy * trains_f) / n_trained,
            num_arrived=n_arrived,
            staleness_mean=allsum(staleness * arrived_f)
            / jnp.maximum(n_arrived, 1.0),
            # mean_delta is already the GLOBAL mean (psum'd above), so its
            # norm is computed identically on every shard.
            update_norm=trees.tree_norm(mean_delta),
            per_client_loss=out.loss * trains_f,
        )
        new_state = AsyncState(
            params=new_params,
            batch_stats=new_stats,
            client_params=new_client_params,
            client_stats=new_client_stats,
            base_params=new_base_params,
            base_stats=new_base_stats,
            opt_state=out.opt_state,
            client_rng=state.client_rng,
            base_version=jnp.where(arrive, new_version, state.base_version),
            version=new_version,
            # Arrivals re-pull and train anew next tick; a client that
            # trained this tick holds its update until it arrives.
            pending=(state.pending | trains) & ~arrive,
            server_opt_state=new_server_opt,
            last_client_loss=jnp.where(
                trains,
                out.loss.astype(jnp.float32),
                state.last_client_loss,
            ),
        )
        return new_state, metrics

    return step


def make_multi_async_step(
    model,
    cfg: RoundConfig,
    steps: int,
    num_ticks: int,
    staleness_power: float = 0.5,
    shuffle: bool = True,
    image_shape: Optional[Tuple[int, ...]] = None,
    layout: str = "presharded",
    axis_name: Optional[str] = None,
    staleness_damping: bool = True,
):
    """``num_ticks`` ticks as ONE ``lax.scan`` program (the async analogue of
    :func:`fedtpu.data.device.make_multi_round_step`): ``arrive`` and
    ``alive`` become ``[num_ticks, clients]`` scan inputs, metrics come back
    stacked."""
    body = make_async_step(
        model, cfg, steps, staleness_power, shuffle, image_shape, layout,
        axis_name=axis_name, staleness_damping=staleness_damping,
    )

    def multi(state, images, labels, idx, mask, weights, arrive, alive,
              data_key):
        def scan_body(st, per_tick):
            arrive_t, alive_t = per_tick
            return body(st, images, labels, idx, mask, weights, arrive_t,
                        alive_t, data_key)

        return jax.lax.scan(
            scan_body, state, (arrive, alive), length=num_ticks
        )

    return multi


class AsyncFederation:
    """Driver for the simulated asynchronous federation (the engine twin of
    ``PrimaryServer.run_async``). Reuses the synchronous engine's data
    pipeline (device-resident dataset + assignment, on-device gather) via a
    delegate :class:`fedtpu.core.engine.Federation`.

    ``speed_sigma`` models client heterogeneity: per-client arrival
    propensities drawn log-normal(0, sigma) once from the seed. sigma=0 is
    homogeneous (uniform random arrivals); larger sigma concentrates
    arrivals on fast clients, so slow clients accumulate staleness — the
    regime FedBuff's discounting is for.
    """

    def __init__(
        self,
        cfg: RoundConfig,
        seed: int = 0,
        buffer_k: int = 2,
        staleness_power: float = 0.5,
        speed_sigma: float = 0.0,
        data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        mesh=None,
        staleness_damping: bool = True,
    ):
        """``mesh``: optional ``jax.sharding.Mesh`` over the clients axis —
        ticks then run under ``shard_map`` with every per-client trajectory
        (diverged params, pull snapshots, momentum) sharded across devices
        and the buffer aggregation as a psum over ICI
        (:func:`fedtpu.parallel.sharded.make_sharded_async_step`).

        Mesh-vs-single-program parity caveat: with
        ``DataConfig(device_layout='presharded')`` (the default) mesh and
        single-program trajectories are BIT-IDENTICAL. With
        ``device_layout='gather'`` they are NOT: the per-shard body folds
        ``lax.axis_index`` into the shuffle key to decorrelate shard
        permutations (see :func:`make_async_step`), so mesh runs draw
        different per-client batch orders than single-program runs —
        statistically equivalent training, but never compare the two
        topologies' gather-layout trajectories update-for-update. A
        one-line notice is logged when this combination is selected.

        ``staleness_damping``: see :func:`make_async_step` — True (default)
        is the FedBuff-paper magnitude-scaling semantics; False reproduces
        the round-4 normalized-mean artifacts."""
        from fedtpu.core.engine import Federation

        _validate(cfg)
        if not 1 <= buffer_k <= cfg.fed.num_clients:
            raise ValueError(
                f"buffer_k must be in [1, num_clients], got {buffer_k}"
            )
        self.cfg = cfg
        self.buffer_k = buffer_k
        self.staleness_power = staleness_power
        self.staleness_damping = staleness_damping
        self.mesh = mesh
        # Delegate builds model/data/partitions (mesh-placed when sharded);
        # its sync jits are lazy and never compiled unless used.
        self._fed = Federation(cfg, seed=seed, data=data, mesh=mesh)
        # Shared telemetry with the delegate: one registry/tracer per
        # federation instance, whichever loop is driving. The status feed
        # is shared too — /statusz shows async ticks through the same board
        # (role re-stamped so the feed says which loop drives).
        self.telemetry = self._fed.telemetry
        self.status = self._fed.status
        self.status.update(role="async_engine")
        self.model = self._fed.model
        sample = jnp.zeros(
            (1,) + tuple(self._fed.images.shape[1:]), jnp.float32
        )
        self.state = init_async_state(
            self.model, cfg, jax.random.PRNGKey(seed), sample, mesh=mesh
        )
        if mesh is None:
            self._step = jax.jit(
                make_async_step(
                    self.model, cfg, self._fed._steps, staleness_power,
                    shuffle=self._fed._shuffle,
                    image_shape=self._fed._img_shape,
                    layout=self._fed._layout,
                    staleness_damping=staleness_damping,
                ),
                donate_argnums=(0,),
            )
        else:
            from fedtpu.parallel.sharded import make_sharded_async_step

            if self._fed._layout == "gather":
                log.info(
                    "async mesh + device_layout='gather': shard-decorrelated "
                    "shuffle keys mean mesh trajectories are statistically "
                    "equivalent but NOT bit-identical to single-program runs "
                    "(presharded layout keeps bit parity)"
                )
            self._step = make_sharded_async_step(
                self.model, cfg, mesh, self._fed._steps, staleness_power,
                shuffle=self._fed._shuffle, image_shape=self._fed._img_shape,
                layout=self._fed._layout,
                staleness_damping=staleness_damping,
            )
        # The delegate's synchronous FederatedState (per-client momentum
        # stack etc.) is never used here and would pin a second full
        # per-client pytree in HBM for the whole run — drop it.
        self._fed._state = None
        self._multi_steps = {}
        rng = np.random.default_rng(seed + 0xA5)
        self._speeds = np.exp(
            rng.normal(0.0, speed_sigma, size=cfg.fed.num_clients)
        )
        self._arrival_rng = np.random.default_rng(cfg.data.seed * 6151 + seed)
        self.alive = self._fed.alive  # shared fault-injection surface
        self._tick_host = 0

    # ------------------------------------------------------------- schedule
    def _arrive_mask(self) -> np.ndarray:
        """Draw this tick's ``buffer_k`` arrivals among live clients,
        probability proportional to speed. Fewer than k live clients -> all
        of them arrive (the edge's hopeless-detection analogue is the
        caller's concern)."""
        live = np.flatnonzero(self.alive)
        arrive = np.zeros((self.cfg.fed.num_clients,), bool)
        if len(live) == 0:
            return arrive
        k = min(self.buffer_k, len(live))
        p = self._speeds[live] / self._speeds[live].sum()
        chosen = self._arrival_rng.choice(live, size=k, replace=False, p=p)
        arrive[chosen] = True
        return arrive

    # ---------------------------------------------------------------- ticks
    def status_snapshot(self) -> dict:
        """``/statusz`` feed (async twin of ``Federation.status_snapshot``)."""
        snap = self.status.snapshot()
        snap["alive"] = self.alive.tolist()
        if self.telemetry.tracer is not None:
            snap["trace_id"] = self.telemetry.tracer.trace_id
        return snap

    def tick(self) -> AsyncMetrics:
        """One server update: everyone trains, ``buffer_k`` clients report."""
        self.status.update(round=self._tick_host, phase="async_tick")
        with self.telemetry.span("async_tick", tick=self._tick_host):
            d_images, d_labels, d_idx, d_mask = (
                self._fed._ensure_device_data()
            )
            self.state, m = self._step(
                self.state,
                d_images,
                d_labels,
                d_idx,
                d_mask,
                self._fed.weights,
                jnp.asarray(self._arrive_mask()),
                jnp.asarray(self.alive.copy()),
                self._fed._data_key,
            )
        self._tick_host += 1
        self.status.update(round=self._tick_host, phase="idle")
        self.telemetry.counter(
            "fedtpu_async_updates_total",
            "simulated FedBuff server updates dispatched",
        ).inc()
        return m

    def run_on_device(self, num_ticks: int) -> AsyncMetrics:
        """``num_ticks`` server updates as ONE fused scan program."""
        if num_ticks < 1:
            raise ValueError(f"num_ticks must be >= 1, got {num_ticks}")
        arrive = np.stack([self._arrive_mask() for _ in range(num_ticks)])
        alive = np.broadcast_to(
            self.alive.copy(), (num_ticks, self.cfg.fed.num_clients)
        ).copy()
        if num_ticks not in self._multi_steps:
            if self.mesh is None:
                self._multi_steps[num_ticks] = jax.jit(
                    make_multi_async_step(
                        self.model, self.cfg, self._fed._steps, num_ticks,
                        self.staleness_power, shuffle=self._fed._shuffle,
                        image_shape=self._fed._img_shape,
                        layout=self._fed._layout,
                        staleness_damping=self.staleness_damping,
                    ),
                    donate_argnums=(0,),
                )
            else:
                from fedtpu.parallel.sharded import make_sharded_async_step

                self._multi_steps[num_ticks] = make_sharded_async_step(
                    self.model, self.cfg, self.mesh, self._fed._steps,
                    self.staleness_power, shuffle=self._fed._shuffle,
                    image_shape=self._fed._img_shape,
                    layout=self._fed._layout, num_ticks=num_ticks,
                    staleness_damping=self.staleness_damping,
                )
        d_images, d_labels, d_idx, d_mask = self._fed._ensure_device_data()
        self.status.update(
            round=self._tick_host, phase="fused_ticks",
            fused_block=num_ticks,
        )
        with self.telemetry.span(
            "fused_ticks", tick=self._tick_host, num_ticks=num_ticks
        ):
            self.state, m = self._multi_steps[num_ticks](
                self.state,
                d_images,
                d_labels,
                d_idx,
                d_mask,
                self._fed.weights,
                jnp.asarray(arrive),
                jnp.asarray(alive),
                self._fed._data_key,
            )
        self._tick_host += num_ticks
        self.status.update(round=self._tick_host, phase="idle")
        self.telemetry.counter(
            "fedtpu_async_updates_total",
            "simulated FedBuff server updates dispatched",
        ).inc(num_ticks)
        return m

    # ----------------------------------------------------- checkpoint/resume
    def load_state(self, tree) -> None:
        """Install a restored :class:`AsyncState` (host pytree from
        :mod:`fedtpu.checkpoint`), re-placing it for the active topology —
        mesh mode re-shards every per-client stack onto the clients axis.

        Host-side scheduling state (the arrival RNG) intentionally does NOT
        ride checkpoints: arrivals model EXTERNAL client timing, so a
        resumed run draws a fresh schedule the same way a restarted real
        deployment would. Everything learned (global + per-client
        trajectories, momentum, versions, pending flags) is in the state.
        """
        host = AsyncState(*tree) if not isinstance(tree, AsyncState) else tree
        if self.mesh is None:
            self.state = jax.tree.map(jnp.asarray, host)
            return
        from fedtpu.parallel.sharded import _put, async_state_specs

        specs = async_state_specs(self.cfg.mesh_axis)

        def place(subtree, spec):
            return jax.tree.map(lambda x: _put(x, self.mesh, spec), subtree)

        self.state = AsyncState(
            *(place(getattr(host, f), getattr(specs, f))
              for f in AsyncState._fields)
        )

    # ----------------------------------------------------------------- eval
    def evaluate(self, images: np.ndarray, labels: np.ndarray):
        """Evaluate the current GLOBAL model."""
        from fedtpu.core.client import batch_eval_arrays

        xs, ys = batch_eval_arrays(
            images, labels, self.cfg.data.eval_batch_size
        )
        loss, acc = self._fed._evaluate(
            self.state.params, self.state.batch_stats, xs, ys
        )
        return float(loss), float(acc)

    def set_alive(self, client: int, alive: bool) -> None:
        self.alive[client] = alive

    @property
    def data_source(self) -> str:
        return self._fed.data_source
