"""Per-client local training.

The reference runs one local epoch per round per client process: reload the
global checkpoint, iterate the round-robin-sharded loader, forward/backward/
SGD-step per batch, save weights (``src/main.py:128-165``). fedtpu's
equivalent is a pure function of (global model, persistent client state, the
round's batches): a ``lax.scan`` over local steps that XLA compiles into one
fused program, designed to sit under ``jax.vmap`` with the leading ``clients``
axis mapped — every simulated client trains simultaneously on its own slice of
the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from fedtpu.config import RoundConfig, resolve_compute_dtype
from fedtpu.core import optim
from fedtpu.ops.losses import softmax_ce_int_labels
from fedtpu.utils import trees

Pytree = Any


class ClientOutput(NamedTuple):
    params: Pytree       # locally-updated weights
    batch_stats: Pytree  # locally-updated BN running stats
    opt_state: optim.SGDState
    loss: jnp.ndarray    # mean masked loss over the round
    accuracy: jnp.ndarray
    num_steps: jnp.ndarray


def make_local_update(
    apply_fn: Callable,
    cfg: RoundConfig,
    stream: bool = False,
    image_shape: Optional[Tuple[int, ...]] = None,
) -> Callable:
    """Build the single-client local-epoch function.

    ``apply_fn(variables, x, train, mutable)`` is the flax ``Module.apply``.
    The returned function is pure and vmappable:

        local_update(global_params, global_stats, opt_state, xs, ys,
                     step_mask, rng, round_idx) -> ClientOutput

    with ``xs: [steps, batch, ...]``, ``ys: [steps, batch]``,
    ``step_mask: [steps]`` (False steps are no-ops so ragged shards keep
    static shapes).

    With ``stream`` set the signature becomes

        local_update(global_params, global_stats, opt_state, images, labels,
                     takes, step_mask, rng, round_idx)

    and each scan step extracts ITS batch only, so the round never
    materialises the full ``[steps, batch, ...]`` tensor — the HBM lever
    that (with remat) fits 64-client resnet18 rounds on one chip (see
    BASELINE.md config 4 / tools/compile_pallas_tpu.py). Two forms:
    ``stream="gather"`` (alias ``True``): ``takes: [steps, batch]`` int32
    indices into the flat device-resident dataset. ``stream="presharded"``:
    ``images``/``labels`` are THIS client's presharded rows ``[2L, ...]``
    (:func:`fedtpu.data.device.preshard_arrays`) and ``takes: [steps]``
    per-step slice offsets — the extraction is a contiguous ``dynamic_slice``
    instead of a row-gather (the measured ~100x per-byte difference on TPU;
    see ``fedtpu/data/device.py``).
    """
    if stream is True:
        stream = "gather"
    mu = cfg.fed.fedprox_mu if cfg.fed.algorithm == "fedprox" else 0.0
    compute_dtype = jnp.dtype(resolve_compute_dtype(cfg))
    # Random crop + flip for CIFAR-style training, fused into the jitted step
    # (the reference augments on the host via torchvision, src/main.py:37-42).
    use_augment = cfg.data.augment and cfg.data.dataset in ("cifar10", "cifar100")

    def loss_fn(params, batch_stats, global_params, x, y, rng):
        # Cast to the compute dtype BEFORE augmentation: the crop/flip are
        # pure selections (exact in any dtype) and the model consumes
        # compute-dtype activations anyway, so augmenting in bf16 is
        # bit-identical to augment-then-cast while halving the augment
        # pipeline's HBM traffic — the largest elementwise fusions in the
        # round-4 on-chip trace (artifacts/MFU_PROFILE_r04_fastcrop.json).
        x = x.astype(compute_dtype)
        if use_augment:
            from fedtpu.data.augment import augment_batch

            aug_rng, rng = jax.random.split(rng)
            x = augment_batch(aug_rng, x, crop=cfg.data.augment_crop)
        # True mixed precision: master params stay f32 in FederatedState;
        # casting them (not just x) at use keeps the WHOLE forward in the
        # compute dtype — flax layers otherwise promote bf16 activations
        # back to f32 against f32 kernels, silently doubling activation HBM
        # and halving MXU rate. Gradients flow through the cast and come out
        # f32. BN running stats stay f32 (they are outputs in train mode).
        if compute_dtype != jnp.float32:
            cast = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        else:
            cast = params
        variables = {"params": cast, "batch_stats": batch_stats}
        logits, updated = apply_fn(
            variables,
            x,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": rng},
        )
        logits = logits.astype(jnp.float32)
        ce = softmax_ce_int_labels(logits, y).mean()
        loss = ce
        if mu > 0.0:
            # FedProx proximal term: mu/2 * ||w - w_global||^2 keeps local
            # iterates near the round's global model (BASELINE config 3).
            loss = loss + 0.5 * mu * trees.tree_sq_norm(
                trees.tree_sub(params, global_params)
            )
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return loss, (updated.get("batch_stats", batch_stats), ce, acc)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def _run_scan(
        global_params, global_stats, opt_state, step_elems, get_xy,
        steps, step_mask, rng, round_idx, anchor=None,
    ) -> ClientOutput:
        # FedProx proximal ANCHOR: defaults to the scan's initial params
        # (synchronous rounds start from the global model, so the two
        # coincide). The async engine passes the client's last-PULLED global
        # explicitly — its scan starts from the client's own diverged
        # trajectory, and anchoring there would make mu a per-tick no-op.
        anchor = global_params if anchor is None else anchor
        lr = cfg.opt.lr_at(round_idx)

        def one_step(carry, batch):
            params, stats, ostate = carry
            elem, live, step_rng = batch
            x, y = get_xy(elem)
            (loss, (new_stats, ce, acc)), grads = grad_fn(
                params, stats, anchor, x, y, step_rng
            )
            if cfg.debug_per_batch:
                # Reference parity (src/utils.py:51-92): per-batch loss/acc
                # lines mid-epoch. A host callback per batch — debugging
                # only; under vmap one line prints per client per batch.
                jax.debug.print(
                    "  batch: loss {l:.4f} acc {a:.4f}", l=ce, a=acc
                )
            new_params, new_ostate = optim.apply(params, grads, ostate, lr, cfg.opt)
            # Masked steps (padding of ragged shards / dead clients) change
            # nothing — the reference equivalent is the client simply not
            # having that batch.
            live_f = live.astype(jnp.float32)
            params = jax.tree.map(
                lambda new, old: jnp.where(live, new, old), new_params, params
            )
            stats = jax.tree.map(
                lambda new, old: jnp.where(live, new, old), new_stats, stats
            )
            ostate = jax.tree.map(
                lambda new, old: jnp.where(live, new, old), new_ostate, ostate
            )
            return (params, stats, ostate), (ce * live_f, acc * live_f, live_f)

        step_rngs = jax.random.split(rng, steps)
        (params, stats, ostate), (ces, accs, lives) = jax.lax.scan(
            one_step,
            (global_params, global_stats, opt_state),
            (step_elems, step_mask, step_rngs),
        )
        n = jnp.maximum(jnp.sum(lives), 1.0)
        return ClientOutput(
            params=params,
            batch_stats=stats,
            opt_state=ostate,
            loss=jnp.sum(ces) / n,
            accuracy=jnp.sum(accs) / n,
            num_steps=jnp.sum(lives),
        )

    if stream == "presharded":
        shape = tuple(image_shape or cfg.image_size)
        batch_size = cfg.data.batch_size

        def local_update(
            global_params: Pytree,
            global_stats: Pytree,
            opt_state: optim.SGDState,
            images: jnp.ndarray,
            labels: jnp.ndarray,
            takes: jnp.ndarray,
            step_mask: jnp.ndarray,
            rng: jax.Array,
            round_idx: jnp.ndarray,
            anchor: Pytree = None,
        ) -> ClientOutput:
            # images/labels are THIS client's [2L, ...] presharded rows;
            # each scan step slices its [batch]-sized window at the step's
            # offset — one contiguous DMA, no gather.
            f_tail = tuple(images.shape[1:])

            def get_xy(o):
                x = jax.lax.dynamic_slice(
                    images, (o,) + (0,) * len(f_tail),
                    (batch_size,) + f_tail,
                )
                if x.ndim == 2:
                    x = x.reshape((batch_size,) + shape)
                y = jax.lax.dynamic_slice(labels, (o,), (batch_size,))
                return x, y

            return _run_scan(
                global_params, global_stats, opt_state,
                takes, get_xy,
                takes.shape[0], step_mask, rng, round_idx, anchor,
            )

    elif stream:
        shape = tuple(image_shape or cfg.image_size)

        def local_update(
            global_params: Pytree,
            global_stats: Pytree,
            opt_state: optim.SGDState,
            images: jnp.ndarray,
            labels: jnp.ndarray,
            takes: jnp.ndarray,
            step_mask: jnp.ndarray,
            rng: jax.Array,
            round_idx: jnp.ndarray,
            anchor: Pytree = None,
        ) -> ClientOutput:
            # Each scan step gathers only its own [batch]-sized slice from
            # the device-resident dataset — nothing [steps, batch, ...]-sized
            # ever exists. The dataset may arrive FLATTENED ([N, H*W*C]):
            # NHWC image tensors pad ~4x under TPU tiled layouts, flat rows
            # tile exactly; the per-batch reshape after the gather is free.
            def get_xy(t):
                x = images[t]
                if x.ndim == 2:
                    x = x.reshape((t.shape[0],) + shape)
                return x, labels[t]

            return _run_scan(
                global_params, global_stats, opt_state,
                takes, get_xy,
                takes.shape[0], step_mask, rng, round_idx, anchor,
            )

    else:

        def local_update(
            global_params: Pytree,
            global_stats: Pytree,
            opt_state: optim.SGDState,
            xs: jnp.ndarray,
            ys: jnp.ndarray,
            step_mask: jnp.ndarray,
            rng: jax.Array,
            round_idx: jnp.ndarray,
            anchor: Pytree = None,
        ) -> ClientOutput:
            return _run_scan(
                global_params, global_stats, opt_state,
                (xs, ys), lambda e: e,
                xs.shape[0], step_mask, rng, round_idx, anchor,
            )

    return local_update


def make_local_update_mega(
    apply_fn: Callable,
    cfg: RoundConfig,
    k: int,
    stream: bool = False,
    image_shape: Optional[Tuple[int, ...]] = None,
) -> Callable:
    """Build the GROUP-of-k local-epoch function (``megabatch_clients=k``).

    Same contract as :func:`make_local_update` but over a group of k
    clients whose per-step batches are concatenated into ONE
    ``[k*batch, ...]`` forward/backward — k skinny matmuls become one wide
    MXU pass, the arithmetic-intensity lever for the small-model zoo
    (every committed roofline profile is bandwidth-bound; see
    docs/PERF_ANALYSIS.md §Roofline). The group shares one parameter
    trajectory per round, which is sound because every client starts each
    round at the same global params; per-example weights keep masked/dead
    members exact.

    Signatures (designed to vmap over the GROUP axis in
    :mod:`fedtpu.core.round`):

        presharded: (gp, gs, opt, images [k, 2L, ...], labels [k, 2L],
                     takes [k, steps], member_mask [k, steps], rng,
                     round_idx)
        gather:     (gp, gs, opt, images [N, ...], labels [N],
                     takes [k, steps, batch], member_mask [k, steps], rng,
                     round_idx)
        non-stream: (gp, gs, opt, xs [k, steps, batch, ...],
                     ys [k, steps, batch], member_mask [k, steps], rng,
                     round_idx)

    returning a :class:`ClientOutput` whose params/stats/opt_state are the
    GROUP trajectory and whose loss/accuracy/num_steps are per-member
    ``[k]`` vectors (the round layer broadcasts the trajectory back onto
    the clients axis).

    Parity contract (test-pinned): at ``k=1`` every array this function
    produces is bit-identical to :func:`make_local_update` — the masked
    per-example loss ``sum(per * w) / max(sum(w), 1)`` reduces over the
    same values in the same order as ``per.mean()`` (w is exactly 1.0,
    multiplying by 1.0 and dividing by the same f32 count preserve bits,
    and the VJP divides the same cotangent by the same count).

    ``k > 1`` approximations (documented, not silent): members share BN
    batch statistics over the ``k*batch`` examples, one augment/dropout
    rng stream (member 0's key), and one optimizer trajectory seeded from
    the mean of the members' buffers; per-member loss/accuracy are
    measured on the member's examples under the GROUP model.
    """
    if stream is True:
        stream = "gather"
    mu = cfg.fed.fedprox_mu if cfg.fed.algorithm == "fedprox" else 0.0
    compute_dtype = jnp.dtype(resolve_compute_dtype(cfg))
    use_augment = cfg.data.augment and cfg.data.dataset in ("cifar10", "cifar100")

    def loss_fn(params, batch_stats, global_params, x, y, exw, rng):
        # exw: [k*batch] per-example weight (1.0 where the example's member
        # is live this step). Same cast-before-augment rationale as the
        # per-client loss_fn.
        x = x.astype(compute_dtype)
        if use_augment:
            from fedtpu.data.augment import augment_batch

            aug_rng, rng = jax.random.split(rng)
            x = augment_batch(aug_rng, x, crop=cfg.data.augment_crop)
        if compute_dtype != jnp.float32:
            cast = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        else:
            cast = params
        variables = {"params": cast, "batch_stats": batch_stats}
        logits, updated = apply_fn(
            variables,
            x,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": rng},
        )
        logits = logits.astype(jnp.float32)
        per = softmax_ce_int_labels(logits, y)  # [k*batch]
        loss = jnp.sum(per * exw) / jnp.maximum(jnp.sum(exw), 1.0)
        if mu > 0.0:
            loss = loss + 0.5 * mu * trees.tree_sq_norm(
                trees.tree_sub(params, global_params)
            )
        correct = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        # Per-member metrics: the member's own examples under the group
        # model (unmasked — dead members' entries are zeroed by the caller).
        ce_m = per.reshape(k, -1).mean(axis=1)  # [k]
        acc_m = correct.reshape(k, -1).mean(axis=1)
        return loss, (updated.get("batch_stats", batch_stats), ce_m, acc_m)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def _run_scan(
        global_params, global_stats, opt_state, step_elems, get_xy,
        steps, member_mask, rng, round_idx, anchor=None,
    ) -> ClientOutput:
        anchor = global_params if anchor is None else anchor
        lr = cfg.opt.lr_at(round_idx)

        def one_step(carry, batch):
            params, stats, ostate = carry
            elem, live_m, step_rng = batch  # live_m: [k]
            x, y = get_xy(elem)
            live_f = live_m.astype(jnp.float32)
            exw = jnp.broadcast_to(
                live_f[:, None], (k, x.shape[0] // k)
            ).reshape(-1)
            (loss, (new_stats, ce_m, acc_m)), grads = grad_fn(
                params, stats, anchor, x, y, exw, step_rng
            )
            new_params, new_ostate = optim.apply(
                params, grads, ostate, lr, cfg.opt
            )
            # The group steps iff ANY member is live; all-masked steps are
            # no-ops exactly like the per-client path.
            live = live_m.any()
            params = jax.tree.map(
                lambda new, old: jnp.where(live, new, old), new_params, params
            )
            stats = jax.tree.map(
                lambda new, old: jnp.where(live, new, old), new_stats, stats
            )
            ostate = jax.tree.map(
                lambda new, old: jnp.where(live, new, old), new_ostate, ostate
            )
            return (params, stats, ostate), (ce_m * live_f, acc_m * live_f, live_f)

        step_rngs = jax.random.split(rng, steps)
        (params, stats, ostate), (ces, accs, lives) = jax.lax.scan(
            one_step,
            (global_params, global_stats, opt_state),
            (step_elems, jnp.swapaxes(member_mask, 0, 1), step_rngs),
        )
        # ces/accs/lives: [steps, k] -> per-member round means.
        n = jnp.maximum(jnp.sum(lives, axis=0), 1.0)
        return ClientOutput(
            params=params,
            batch_stats=stats,
            opt_state=ostate,
            loss=jnp.sum(ces, axis=0) / n,
            accuracy=jnp.sum(accs, axis=0) / n,
            num_steps=jnp.sum(lives, axis=0),
        )

    if stream == "presharded":
        shape = tuple(image_shape or cfg.image_size)
        batch_size = cfg.data.batch_size

        def local_update(
            global_params: Pytree,
            global_stats: Pytree,
            opt_state: optim.SGDState,
            images: jnp.ndarray,
            labels: jnp.ndarray,
            takes: jnp.ndarray,
            member_mask: jnp.ndarray,
            rng: jax.Array,
            round_idx: jnp.ndarray,
            anchor: Pytree = None,
        ) -> ClientOutput:
            # images/labels: the k members' [2L, ...] presharded rows
            # stacked [k, 2L, ...]; per step, slice each member's [batch]
            # window and concatenate along the example axis.
            f_tail = tuple(images.shape[2:])

            def slice_one(img, lab, o):
                x = jax.lax.dynamic_slice(
                    img, (o,) + (0,) * len(f_tail), (batch_size,) + f_tail
                )
                y = jax.lax.dynamic_slice(lab, (o,), (batch_size,))
                return x, y

            def get_xy(o):  # o: [k] per-member offsets
                xs, ys = jax.vmap(slice_one)(images, labels, o)
                x = xs.reshape((k * batch_size,) + f_tail)
                if x.ndim == 2:
                    x = x.reshape((k * batch_size,) + shape)
                return x, ys.reshape(k * batch_size)

            return _run_scan(
                global_params, global_stats, opt_state,
                jnp.swapaxes(takes, 0, 1), get_xy,
                takes.shape[1], member_mask, rng, round_idx, anchor,
            )

    elif stream:
        shape = tuple(image_shape or cfg.image_size)

        def local_update(
            global_params: Pytree,
            global_stats: Pytree,
            opt_state: optim.SGDState,
            images: jnp.ndarray,
            labels: jnp.ndarray,
            takes: jnp.ndarray,
            member_mask: jnp.ndarray,
            rng: jax.Array,
            round_idx: jnp.ndarray,
            anchor: Pytree = None,
        ) -> ClientOutput:
            def get_xy(t):  # t: [k, batch] indices into the flat dataset
                flat_t = t.reshape(-1)
                x = images[flat_t]
                if x.ndim == 2:
                    x = x.reshape((flat_t.shape[0],) + shape)
                return x, labels[flat_t]

            return _run_scan(
                global_params, global_stats, opt_state,
                jnp.swapaxes(takes, 0, 1), get_xy,
                takes.shape[1], member_mask, rng, round_idx, anchor,
            )

    else:

        def local_update(
            global_params: Pytree,
            global_stats: Pytree,
            opt_state: optim.SGDState,
            xs: jnp.ndarray,
            ys: jnp.ndarray,
            member_mask: jnp.ndarray,
            rng: jax.Array,
            round_idx: jnp.ndarray,
            anchor: Pytree = None,
        ) -> ClientOutput:
            # xs: [k, steps, batch, ...] -> scanned [k, batch, ...] slabs.
            def get_xy(e):
                x, y = e
                return x.reshape((-1,) + x.shape[2:]), y.reshape(-1)

            return _run_scan(
                global_params, global_stats, opt_state,
                (jnp.swapaxes(xs, 0, 1), jnp.swapaxes(ys, 0, 1)), get_xy,
                xs.shape[1], member_mask, rng, round_idx, anchor,
            )

    return local_update


def batch_eval_arrays(images, labels, batch_size: int):
    """Shape an eval set into ``[num_batches, batch, ...]`` for the jitted
    evaluator, dropping the ragged tail. Raises (rather than mis-reshaping)
    when the set is smaller than one batch."""
    import numpy as np

    nb = len(images) // batch_size
    if nb == 0:
        raise ValueError(
            f"eval set of {len(images)} examples is smaller than "
            f"eval_batch_size={batch_size}"
        )
    xs = np.asarray(images[: nb * batch_size]).reshape(
        (nb, batch_size) + images.shape[1:]
    )
    ys = np.asarray(labels[: nb * batch_size]).reshape((nb, batch_size))
    return jnp.asarray(xs), jnp.asarray(ys)


def make_eval_fn(apply_fn: Callable, cfg: RoundConfig) -> Callable:
    """Batched evaluation of a model snapshot (parity: ``src/main.py:167-191``,
    the eval the reference runs on every client after each SendModel)."""

    def eval_step(params, batch_stats, x, y):
        variables = {"params": params, "batch_stats": batch_stats}
        logits = apply_fn(variables, x, train=False, mutable=False)
        ce = softmax_ce_int_labels(logits.astype(jnp.float32), y)
        correct = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        return ce.sum(), correct.sum()

    @jax.jit
    def evaluate(params, batch_stats, xs, ys):
        """xs: [num_batches, batch, ...] — returns (mean_loss, accuracy)."""
        losses, corrects = jax.lax.map(
            lambda b: eval_step(params, batch_stats, b[0], b[1]), (xs, ys)
        )
        n = ys.size
        return jnp.sum(losses) / n, jnp.sum(corrects) / n

    return evaluate
