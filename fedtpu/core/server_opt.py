"""Server-side optimization of the aggregated update (the FedOpt family).

The reference server applies the uniform mean of client states directly as
the new global model (``src/server.py:163-179``) — that is FedAvg, i.e.
``server_optimizer="none"``. This module adds the standard generalisation
(Reddi et al., "Adaptive Federated Optimization", 2021): treat the mean
client delta as a pseudo-gradient and feed it to a server optimizer —
SGD+momentum ("FedAvgM") or Adam ("FedAdam"). Runs inside the jitted round
step; its state (server momentum / Adam moments over the GLOBAL model, not
per-client) rides in ``FederatedState.server_opt_state`` and is replicated
across mesh shards.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import optax

from fedtpu.config import FedConfig

Pytree = Any


def make_server_optimizer(fed: FedConfig) -> Optional[optax.GradientTransformation]:
    """The optax transform for ``fed.server_optimizer``, or None for plain
    FedAvg (apply the mean delta directly — reference semantics)."""
    if fed.server_optimizer == "none":
        return None
    if fed.server_optimizer == "momentum":
        return optax.sgd(fed.server_lr, momentum=fed.server_momentum)
    if fed.server_optimizer == "adam":
        return optax.adam(
            fed.server_lr, b1=fed.server_momentum, b2=fed.server_beta2,
            eps=fed.server_eps,
        )
    if fed.server_optimizer == "yogi":
        return optax.yogi(
            fed.server_lr, b1=fed.server_momentum, b2=fed.server_beta2,
            eps=fed.server_eps,
        )
    raise ValueError(
        f"unknown server_optimizer {fed.server_optimizer!r}; "
        "have none | momentum | adam | yogi"
    )


def init(fed: FedConfig, params: Pytree) -> Pytree:
    """Initial ``server_opt_state`` — the empty pytree for plain FedAvg."""
    opt = make_server_optimizer(fed)
    return () if opt is None else opt.init(params)


def apply(
    opt: Optional[optax.GradientTransformation],
    params: Pytree,
    mean_delta: Pytree,
    opt_state: Pytree,
) -> Tuple[Pytree, Pytree]:
    """New global params from the aggregated delta.

    ``opt=None``: ``params + mean_delta`` (FedAvg). Otherwise the delta's
    negation is the pseudo-gradient (optax descends, FedOpt ascends along the
    delta); with ``sgd(lr=1, momentum=0)`` this reduces exactly to FedAvg.
    """
    from fedtpu.utils import trees

    if opt is None:
        return trees.tree_add(params, mean_delta), opt_state
    grad = jax.tree.map(lambda d: -d, mean_delta)
    updates, new_state = opt.update(grad, opt_state, params)
    return optax.apply_updates(params, updates), new_state
