from fedtpu.core.engine import Federation
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    init_state,
    make_round_step,
)
from fedtpu.core.client import make_eval_fn, make_local_update

__all__ = [
    "Federation",
    "FederatedState",
    "RoundBatch",
    "RoundMetrics",
    "init_state",
    "make_round_step",
    "make_eval_fn",
    "make_local_update",
]
