from fedtpu.core.async_engine import (
    AsyncFederation,
    AsyncMetrics,
    AsyncState,
    make_async_step,
)
from fedtpu.core.engine import Federation
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    init_state,
    make_round_step,
)
from fedtpu.core.client import make_eval_fn, make_local_update
from fedtpu.core.solo import SoloTrainer, run_solo

__all__ = [
    "SoloTrainer",
    "run_solo",
    "AsyncFederation",
    "AsyncMetrics",
    "AsyncState",
    "make_async_step",
    "Federation",
    "FederatedState",
    "RoundBatch",
    "RoundMetrics",
    "init_state",
    "make_round_step",
    "make_eval_fn",
    "make_local_update",
]
