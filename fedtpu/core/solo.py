"""Standalone single-node training — the reference's vestigial-but-present
``main.py`` path (``src/main.py:104-125`` train, ``:193-228`` test with
best-accuracy checkpointing, ``:87-96`` ``--resume``), kept as a first-class
surface: train one model on the full dataset, evaluate per epoch, checkpoint
whenever test accuracy improves.

Jitted train step over shuffled epoch batches; the optimizer and cosine
schedule are the shared torch-semantics implementation
(:mod:`fedtpu.core.optim`), so solo and federated training use identical
update math.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.utils.platform import shard_map
from fedtpu import models as model_zoo
from fedtpu.config import RoundConfig
from fedtpu.core import optim
from fedtpu.ops.losses import softmax_ce_int_labels
from fedtpu.core.client import batch_eval_arrays, make_eval_fn
from fedtpu.data import dataset_info, load
from fedtpu.transport import wire
from fedtpu.utils.metrics import MetricsLogger


class SoloTrainer:
    """Single-model SGD trainer with best-acc checkpointing.

    >>> t = SoloTrainer(cfg, checkpoint_path="checkpoint/model.fckpt")
    >>> for epoch in range(200):
    ...     t.train_epoch()
    ...     t.test_epoch()   # saves when best
    """

    def __init__(
        self,
        cfg: RoundConfig,
        seed: int = 0,
        checkpoint_path: Optional[str] = None,
        resume: bool = False,
        mesh=None,
    ):
        """``mesh``: optional 1-D ``jax.sharding.Mesh`` (any axis name) for
        intra-node batch data parallelism: each step's batch shards across
        the mesh, gradients/BN-stats/metrics pmean over it, and the
        replicated update is identical on every device — the TPU-native
        form of the reference's vestigial ``torch.nn.DataParallel`` wrap
        (``src/main.py:79-81``; SURVEY §2d "intra-client DP"). The mesh
        size must divide the batch size.

        Numerics vs single-device: bit-identical for deterministic models
        (no BN, no dropout, augment off — test-pinned on mlp). BatchNorm
        models normalize each SHARD's sub-batch — the same semantics as
        torch DataParallel, whose replicas also normalize their sub-batches
        — so they match the reference's mechanism, not the single-device
        trajectory (running stats here are the pmean over shards).
        Dropout/augmentation RNG is fold_in-decorrelated per shard."""
        self.cfg = cfg
        if mesh is not None and cfg.data.batch_size % mesh.devices.size:
            # Validate before the model build / dataset load below.
            raise ValueError(
                f"batch_size={cfg.data.batch_size} not divisible by "
                f"mesh size {mesh.devices.size}"
            )
        self.model = model_zoo.create(
            cfg.model, num_classes=cfg.num_classes, remat=cfg.remat
        )
        self.images, self.labels = load(
            cfg.data.dataset, "train", seed=cfg.data.seed, num=cfg.data.num_examples
        )
        self.test_images, self.test_labels = load(
            cfg.data.dataset, "test", seed=cfg.data.seed, num=cfg.data.num_examples
        )
        sample = jnp.zeros((1,) + tuple(self.images.shape[1:]), jnp.float32)
        variables = self.model.init(jax.random.PRNGKey(seed), sample, train=False)
        self.params = variables["params"]
        self.batch_stats = variables.get("batch_stats", {})
        self.opt_state = optim.init(self.params, cfg.opt)
        self.rng = jax.random.PRNGKey(seed + 1)
        self.epoch = 0
        self.best_acc = 0.0
        self.checkpoint_path = checkpoint_path
        if mesh is None:
            self._train_step = jax.jit(self._make_train_step())
        else:
            from jax.sharding import PartitionSpec as P

            axis = mesh.axis_names[0]
            body = self._make_train_step(axis_name=axis)
            self._train_step = jax.jit(
                shard_map(
                    body,
                    mesh=mesh,
                    in_specs=(
                        P(),        # params (replicated)
                        P(),        # batch_stats
                        P(),        # opt_state
                        P(axis),    # x — batch axis sharded
                        P(axis),    # y
                        P(),        # rng
                        P(),        # epoch_idx
                    ),
                    out_specs=(P(), P(), P(), P(), P()),
                    check_vma=False,
                )
            )
        self._evaluate = make_eval_fn(self.model.apply, cfg)
        if resume and checkpoint_path and os.path.exists(checkpoint_path):
            self.load_checkpoint(checkpoint_path)

    # ------------------------------------------------------------- training
    def _make_train_step(self, axis_name: Optional[str] = None):
        """``axis_name`` set = the per-shard body for batch data
        parallelism: grads/BN-stats/metrics pmean over the axis so the
        (replicated) update matches the full-batch computation exactly."""
        cfg = self.cfg
        use_augment = cfg.data.augment and cfg.data.dataset in (
            "cifar10",
            "cifar100",
        )

        def loss_fn(params, batch_stats, x, y, rng):
            if axis_name is not None:
                # Decorrelate ALL per-shard randomness (augmentation crops
                # and dropout masks); a replicated key would drop the same
                # positions on every shard's sub-batch.
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
            if use_augment:
                from fedtpu.data.augment import augment_batch

                aug_rng, rng = jax.random.split(rng)
                x = augment_batch(aug_rng, x)
            variables = {"params": params, "batch_stats": batch_stats}
            logits, updated = self.model.apply(
                variables, x, train=True, mutable=["batch_stats"],
                rngs={"dropout": rng},
            )
            ce = softmax_ce_int_labels(logits.astype(jnp.float32), y).mean()
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return ce, (updated.get("batch_stats", batch_stats), acc)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def step(params, batch_stats, opt_state, x, y, rng, epoch_idx):
            (loss, (stats, acc)), grads = grad_fn(params, batch_stats, x, y, rng)
            if axis_name is not None:
                grads = jax.lax.pmean(grads, axis_name)
                stats = jax.lax.pmean(stats, axis_name)
                loss = jax.lax.pmean(loss, axis_name)
                acc = jax.lax.pmean(acc, axis_name)
            lr = cfg.opt.lr_at(epoch_idx)
            params, opt_state = optim.apply(params, grads, opt_state, lr, cfg.opt)
            return params, stats, opt_state, loss, acc

        return step

    def train_epoch(self) -> Tuple[float, float]:
        """One shuffled epoch (parity: ``train(epoch)``, ``src/main.py:104-125``).
        Returns (mean loss, mean accuracy)."""
        bs = self.cfg.data.batch_size
        n = len(self.images)
        self.rng, shuffle_rng = jax.random.split(self.rng)
        order = np.asarray(
            jax.random.permutation(shuffle_rng, n)
        )
        losses, accs = [], []
        for i in range(n // bs):
            take = order[i * bs : (i + 1) * bs]
            self.rng, step_rng = jax.random.split(self.rng)
            self.params, self.batch_stats, self.opt_state, loss, acc = (
                self._train_step(
                    self.params,
                    self.batch_stats,
                    self.opt_state,
                    jnp.asarray(self.images[take]),
                    jnp.asarray(self.labels[take]),
                    step_rng,
                    jnp.asarray(self.epoch, jnp.int32),
                )
            )
            losses.append(float(loss))
            accs.append(float(acc))
        self.epoch += 1
        return float(np.mean(losses)), float(np.mean(accs))

    # ------------------------------------------------------------------ eval
    def test_epoch(self) -> Tuple[float, float]:
        """Evaluate; checkpoint when test accuracy beats the best so far
        (parity: ``test(epoch)``, ``src/main.py:193-228``)."""
        xs, ys = batch_eval_arrays(
            self.test_images, self.test_labels, self.cfg.data.eval_batch_size
        )
        loss, acc = self._evaluate(self.params, self.batch_stats, xs, ys)
        loss, acc = float(loss), float(acc)
        if acc > self.best_acc:
            self.best_acc = acc
            if self.checkpoint_path:
                self.save_checkpoint(self.checkpoint_path)
        return loss, acc

    # ------------------------------------------------------------ checkpoint
    def _state_tree(self):
        return {
            "params": self.params,
            "batch_stats": self.batch_stats,
            "momentum": self.opt_state.momentum,
            "epoch": jnp.asarray(self.epoch, jnp.int32),
            "best_acc": jnp.asarray(self.best_acc, jnp.float32),
        }

    def save_checkpoint(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(wire.encode(self._state_tree(), compress=True))
        os.replace(tmp, path)

    def load_checkpoint(self, path: str) -> None:
        """Resume weights + optimizer + epoch + best accuracy (parity:
        ``--resume``, ``src/main.py:87-96``)."""
        like = jax.tree.map(np.asarray, self._state_tree())
        with open(path, "rb") as fh:
            tree = wire.decode(fh.read(), like)
        self.params = jax.tree.map(jnp.asarray, tree["params"])
        self.batch_stats = jax.tree.map(jnp.asarray, tree["batch_stats"])
        self.opt_state = optim.SGDState(
            momentum=jax.tree.map(jnp.asarray, tree["momentum"])
        )
        self.epoch = int(tree["epoch"])
        self.best_acc = float(tree["best_acc"])


def run_solo(
    cfg: RoundConfig,
    epochs: int,
    seed: int = 0,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
    logger: Optional[MetricsLogger] = None,
    mesh=None,
) -> SoloTrainer:
    trainer = SoloTrainer(
        cfg, seed=seed, checkpoint_path=checkpoint_path, resume=resume,
        mesh=mesh,
    )
    for _ in range(epochs):
        tr_loss, tr_acc = trainer.train_epoch()
        te_loss, te_acc = trainer.test_epoch()
        if logger is not None:
            logger.log(
                trainer.epoch,
                train_loss=tr_loss,
                train_acc=tr_acc,
                test_loss=te_loss,
                test_acc=te_acc,
                best_acc=trainer.best_acc,
            )
    return trainer
