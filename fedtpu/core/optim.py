"""Local optimizer with reference-exact semantics.

The reference trainer uses torch ``SGD(lr, momentum=0.9, weight_decay=5e-4)``
with ``CosineAnnealingLR(T_max=200)`` (``src/main.py:99-101``). Two semantics
matter for parity and are easy to get wrong:

1. torch applies weight decay by adding ``wd * param`` to the gradient
   *before* the momentum buffer update (coupled, not AdamW-style decoupled).
2. The reference *persists* optimizer momentum across rounds inside each
   client process while *reloading* weights from the global checkpoint each
   round (``src/main.py:130-134`` reloads ``net``; ``optimizer`` is the module
   global from ``src/main.py:99``). fedtpu reproduces this by carrying the
   momentum buffers in per-client federated state (see
   :mod:`fedtpu.core.round`).

Implemented directly (not via optax.sgd) so the update order is explicit and
the state is a bare pytree of buffers — trivially vmappable over clients.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from fedtpu.config import OptimizerConfig

Pytree = Any


class SGDState(NamedTuple):
    momentum: Pytree  # same structure as params


def _momentum_dtype(cfg: Optional[OptimizerConfig]) -> jnp.dtype:
    name = "float32" if cfg is None else cfg.momentum_dtype
    if name not in ("float32", "bfloat16"):
        raise ValueError(
            f"unknown momentum_dtype {name!r}; have float32 | bfloat16"
        )
    return jnp.dtype(name)


def init(params: Pytree, cfg: Optional[OptimizerConfig] = None) -> SGDState:
    """Zero buffers in ``cfg.momentum_dtype`` (f32 when ``cfg`` is omitted —
    the reference-parity default)."""
    dtype = _momentum_dtype(cfg)
    return SGDState(
        momentum=jax.tree.map(lambda p: jnp.zeros(p.shape, dtype), params)
    )


def apply(
    params: Pytree,
    grads: Pytree,
    state: SGDState,
    lr,
    cfg: OptimizerConfig,
) -> Tuple[Pytree, SGDState]:
    """One torch-semantics SGD step. ``lr`` may be a traced scalar.

    With ``cfg.momentum_dtype='bfloat16'`` (non-parity, opt-in) the stored
    buffers are bf16 but the update math stays f32: the buffer is upcast,
    accumulated in f32, applied to the (f32) params, and only the STORED
    buffer is rounded — so the mode is exactly one bf16 round-trip per
    buffer per step, never a low-precision accumulation.
    """
    store_dtype = _momentum_dtype(cfg)
    decayed = jax.tree.map(lambda g, p: g + cfg.weight_decay * p, grads, params)
    new_buf = jax.tree.map(
        lambda b, g: cfg.momentum * b.astype(jnp.float32) + g,
        state.momentum, decayed,
    )
    if cfg.nesterov:
        direction = jax.tree.map(
            lambda g, b: g + cfg.momentum * b, decayed, new_buf
        )
    else:
        direction = new_buf
    new_params = jax.tree.map(lambda p, d: p - lr * d, params, direction)
    stored = jax.tree.map(lambda b: b.astype(store_dtype), new_buf)
    return new_params, SGDState(momentum=stored)
