"""Local optimizer with reference-exact semantics.

The reference trainer uses torch ``SGD(lr, momentum=0.9, weight_decay=5e-4)``
with ``CosineAnnealingLR(T_max=200)`` (``src/main.py:99-101``). Two semantics
matter for parity and are easy to get wrong:

1. torch applies weight decay by adding ``wd * param`` to the gradient
   *before* the momentum buffer update (coupled, not AdamW-style decoupled).
2. The reference *persists* optimizer momentum across rounds inside each
   client process while *reloading* weights from the global checkpoint each
   round (``src/main.py:130-134`` reloads ``net``; ``optimizer`` is the module
   global from ``src/main.py:99``). fedtpu reproduces this by carrying the
   momentum buffers in per-client federated state (see
   :mod:`fedtpu.core.round`).

Implemented directly (not via optax.sgd) so the update order is explicit and
the state is a bare pytree of buffers — trivially vmappable over clients.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from fedtpu.config import OptimizerConfig

Pytree = Any


class SGDState(NamedTuple):
    momentum: Pytree  # same structure as params


def init(params: Pytree) -> SGDState:
    return SGDState(momentum=jax.tree.map(jnp.zeros_like, params))


def apply(
    params: Pytree,
    grads: Pytree,
    state: SGDState,
    lr,
    cfg: OptimizerConfig,
) -> Tuple[Pytree, SGDState]:
    """One torch-semantics SGD step. ``lr`` may be a traced scalar."""

    decayed = jax.tree.map(lambda g, p: g + cfg.weight_decay * p, grads, params)
    new_buf = jax.tree.map(lambda b, g: cfg.momentum * b + g, state.momentum, decayed)
    if cfg.nesterov:
        direction = jax.tree.map(
            lambda g, b: g + cfg.momentum * b, decayed, new_buf
        )
    else:
        direction = new_buf
    new_params = jax.tree.map(lambda p, d: p - lr * d, params, direction)
    return new_params, SGDState(momentum=new_buf)
