"""fedtpu — a TPU-native federated-learning framework.

Re-designed from scratch for TPU (JAX / XLA / pjit / Pallas) with the
capabilities of the reference gRPC parameter-server system
(``amolahinge/739-839-federated-learning-using-grpc``):

- Synchronous FedAvg over N federated clients (reference: ``src/server.py:113-179``)
  becomes a single jitted round step: ``jax.vmap`` of local SGD over a leading
  ``clients`` axis plus a masked, weighted ``lax.psum`` mean over the device mesh.
- The 18-architecture CIFAR CNN zoo (reference: ``src/models/``) is rebuilt in
  ``flax.linen`` (see :mod:`fedtpu.models`).
- Client failure detection / heartbeats (reference: ``src/server.py:78-101``)
  become a participation mask feeding the weighted aggregate, plus a real
  failure-detector state machine on the gRPC edge (:mod:`fedtpu.ft`).
- Update compression (``-c Y``, reference: ``src/server.py:104-107``) becomes
  on-device top-k sparsification / int8 quantization with error feedback
  (:mod:`fedtpu.ops`), applied to client deltas *before* aggregation.
- gRPC survives only at the cross-pod edge, proto-compatible with the
  reference's ``federated.proto`` (:mod:`fedtpu.transport`).
"""

from fedtpu.version import __version__

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RoundConfig,
)

__all__ = [
    "__version__",
    "DataConfig",
    "FedConfig",
    "OptimizerConfig",
    "RoundConfig",
    "Federation",
    "SoloTrainer",
]


def __getattr__(name):
    # Lazy: `fedtpu.Federation` / `fedtpu.SoloTrainer` without paying the
    # jax/flax import chain for config-only users.
    if name == "Federation":
        from fedtpu.core import Federation

        return Federation
    if name == "SoloTrainer":
        from fedtpu.core import SoloTrainer

        return SoloTrainer
    raise AttributeError(f"module 'fedtpu' has no attribute {name!r}")
